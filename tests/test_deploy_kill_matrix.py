"""Deploy kill-matrix: SIGKILL the deploy controller and replicas at
injected chaos points (PROGEN_CHAOS) mid-canary and mid-promote, with
live traffic flowing through the router, and assert the continuous-
deployment invariants across the whole fleet:

  1. the fleet converges to exactly ONE checkpoint digest — the new one
     when the pipeline completes (a restarted controller resumes from
     the ledger), the old one when it rolls back (a dead canary's
     weights never reach the rest of the fleet);
  2. zero lost accepted requests across every wave — requests riding a
     weight swap settle via between-step ``commit_params``, requests on
     a killed replica hand off to survivors;
  3. traffic before the deploy is bit-identical to ``sample_fast`` on
     the OLD weights, traffic after convergence to the NEW weights
     (after rollback: still the old) — the swap is atomic per stream;
  4. the surviving replicas' ``decode_compile_count`` stays at 1 — the
     swap recompiled nothing;
  5. a rollback pages ``deploy_rollback`` through the alert sink
     exactly once, and the condemned candidate is never retried.

Real subprocesses throughout: ``cli/serve --reload_pin`` replicas, one
``cli/router`` front, and ``cli/deploy`` as the controller. Traffic
runs in waves so parity has a stable weight identity: wave1 drains
before the candidate is published, wave2 rides the deploy (exactly-once
only — its streams may span the swap), wave3 runs after the fleet
settles. One controller-kill and one canary-kill case run in tier-1;
their phase-shifted twins are ``slow``.
"""

import json
import subprocess
import sys
import time
from pathlib import Path

import pytest

from tests.test_router_kill_matrix import (
    KILL_CFG,
    _decode_compile_count,
    _env,
    _journal_accepts,
    _parse_events,
    _public_id,
    _pump,
    _spawn_router,
    _stop_replica,
    _wait_sockets,
)

@pytest.fixture(scope="module")
def models():
    """One model, two weight sets: A (the fleet baseline) and B (the
    candidate). Saved per-test — the store is mutated mid-test."""
    import jax
    import jax.numpy as jnp
    from flax.core import meta

    from progen_tpu.config import ProGenConfig
    from progen_tpu.models.progen import ProGen

    config = ProGenConfig(**KILL_CFG)
    model = ProGen(config)
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, config.seq_len), jnp.int32)
    )
    params_a = meta.unbox(variables)["params"]
    params_b = jax.tree.map(lambda x: x * 1.5, params_a)
    return {"model": model, "config": config,
            "a": params_a, "b": params_b}


def _save(ck, params, step, config):
    from progen_tpu.checkpoint import Package, get_checkpoint_fns

    _, _, save = get_checkpoint_fns(str(ck))
    return Path(
        save(Package(step, {"params": params}, config.to_dict(), "dkm"))
    ).name


def _spawn_pinned_replica(ck, rdir, *, chaos=""):
    """A serve replica that honors its ``reload.pin`` control file —
    the deploy controller's per-replica seam."""
    rdir = Path(rdir)
    rdir.mkdir(parents=True, exist_ok=True)
    args = [
        sys.executable, "-m", "progen_tpu.cli.serve",
        "--checkpoint_path", str(ck),
        "--max-slots", "2", "--max-queue", "16", "--max-len", "24",
        "--socket", str(rdir / "serve.sock"),
        "--journal_dir", str(rdir),
        "--prom_file", str(rdir / "metrics.prom"),
        "--metrics-every", "2",
        "--reload_watch", "0.5",
        "--reload_pin", str(rdir / "reload.pin"),
    ]
    return subprocess.Popen(
        args, stdin=subprocess.DEVNULL, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, env=_env(chaos), text=True, bufsize=1,
    )


def _spawn_controller(ck, rdirs, deploy_dir, *, chaos="", alerts=None,
                      policy=None, interval=0.3):
    """cli/deploy over explicit --replica name=DIR specs; stderr goes
    to ``deploy_dir/controller.log`` (appended across restarts) so a
    SIGKILL cannot strand a half-full pipe."""
    deploy_dir = Path(deploy_dir)
    deploy_dir.mkdir(parents=True, exist_ok=True)
    args = [
        sys.executable, "-m", "progen_tpu.cli.deploy",
        "--checkpoint_path", str(ck),
        "--deploy_dir", str(deploy_dir),
        "--interval", str(interval),
    ]
    for i, rdir in enumerate(rdirs):
        args += ["--replica", f"replica{i}={rdir}"]
    if alerts is not None:
        args += ["--alerts", str(alerts)]
    if policy is not None:
        args += ["--policy", str(policy)]
    return subprocess.Popen(
        args, stdin=subprocess.DEVNULL, stdout=subprocess.DEVNULL,
        stderr=open(deploy_dir / "controller.log", "a"),
        env=_env(chaos),
    )


def _ledger(deploy_dir):
    from progen_tpu.telemetry.trace import iter_jsonl

    path = Path(deploy_dir) / "deploy.jsonl"
    if not path.exists():
        return []
    return [r for r in iter_jsonl(path) if r.get("ev") == "deploy"]


def _wait_ledger(deploy_dir, pred, timeout_s, what):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        recs = _ledger(deploy_dir)
        if pred(recs):
            return recs
    log = Path(deploy_dir) / "controller.log"
    tail = log.read_text()[-2000:] if log.exists() else ""
    pytest.fail(f"ledger never showed {what}:\n"
                f"{[r.get('op') for r in _ledger(deploy_dir)]}\n{tail}")


def _ack_of(rdir):
    try:
        return json.loads((Path(rdir) / "reload.pin.ack").read_text())
    except (OSError, ValueError):
        return None


def _wait_ack(rdir, ckpt, timeout_s=120):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        ack = _ack_of(rdir)
        if ack and ack.get("pin") == ckpt \
                and ack.get("status") == "committed":
            return ack
        time.sleep(0.25)
    pytest.fail(f"{rdir} never acked {ckpt}: last {_ack_of(rdir)}")


def _digest_gauge_of(ck, name):
    from progen_tpu.checkpoint import checkpoint_digest, digest_gauge

    return digest_gauge(checkpoint_digest(Path(ck) / name))


def _prom_digest(rdir):
    import re

    text = (Path(rdir) / "metrics.prom").read_text()
    m = re.search(
        r"^progen_serve_checkpoint_digest (\S+)$", text, re.M
    )
    assert m, text
    return float(m.group(1))


def _send_wave(router, ids, length=16):
    lines = [
        json.dumps({
            "id": rid, "prime": "MKV", "length": length,
            "seed": 70 + j,
        })
        for j, rid in enumerate(ids)
    ]
    router.stdin.write("\n".join(lines) + "\n")
    router.stdin.flush()


def _wait_done(router, out_lines, err_lines, ids, timeout_s=600):
    want = set(ids)

    def settled():
        _, done, rejected = _parse_events(out_lines)
        return want <= (set(done) | {r["id"] for r in rejected})

    assert _pump(router, out_lines, err_lines, settled, timeout_s), (
        f"wave {sorted(want)} never settled:\n"
        + "\n".join(err_lines)[-2000:]
    )


def _assert_wave_parity(models, params, rdirs, tokens, ids):
    """Every token the fleet emitted for ``ids`` matches the
    uninterrupted ``sample_fast`` stream of its ORIGINAL journaled
    accept, computed on ``params``."""
    import jax.numpy as jnp
    import numpy as np

    from progen_tpu.sampling import sample_fast

    originals = {}
    for rdir in rdirs:
        for jid, acc in _journal_accepts(rdir).items():
            pub = _public_id(jid)
            if pub not in originals or \
                    len(acc["prime"]) < len(originals[pub]["prime"]):
                originals[pub] = acc
    want = set(ids)
    assert want <= set(originals), (sorted(want), sorted(originals))
    refs = {}
    for pub in want:
        acc = originals[pub]
        refs[pub] = np.asarray(sample_fast(
            jnp.asarray(acc["key"], jnp.uint32),
            models["model"], params,
            jnp.asarray(acc["prime"], jnp.int32), acc["length"],
            top_k=acc["top_k"], add_bos=acc["add_bos"],
            temperature=acc["temperature"], top_p=acc["top_p"],
        ))
    for rid, ix, tok in tokens:
        if rid not in want:
            continue
        assert refs[rid][ix] == tok, (rid, ix, tok, int(refs[rid][ix]))


def _assert_exactly_once(out_lines, all_ids):
    tokens, done, rejected = _parse_events(out_lines)
    assert sorted(done) == sorted(all_ids), (sorted(done), rejected)
    assert rejected == []
    pairs = [(i, ix) for i, ix, _ in tokens]
    assert len(set(pairs)) == len(pairs)
    return tokens


def _rollback_policy(tmp_path):
    """Short ack timeout so a dead replica rolls the deploy back inside
    the test budget (production default is 120s)."""
    p = tmp_path / "deploy_policy.toml"
    p.write_text("[deploy]\nack_timeout_s = 10.0\n")
    return p


def _alert_kinds(path):
    from progen_tpu.telemetry.trace import iter_jsonl

    if not Path(path).exists():
        return []
    return [
        (r.get("kind"), r.get("objective"))
        for r in iter_jsonl(path) if r.get("ev") == "alert"
    ]


class TestDeployKillMatrix:
    def test_controller_sigkill_mid_promote_converges(
        self, models, tmp_path
    ):
        """The tier-1 marquee case: the controller SIGKILLs entering
        its first promote — after the canary committed the candidate
        but before the rest of the fleet was told. A restarted
        controller must replay the ledger and finish the rollout:
        single fleet-wide digest, zero lost requests, bit-parity per
        wave, compile-flat replicas."""
        ck = tmp_path / "ck"
        name_a = _save(ck, models["a"], 0, models["config"])
        rdirs = [tmp_path / "r0", tmp_path / "r1"]
        deploy_dir = tmp_path / "deploy"
        procs = [_spawn_pinned_replica(ck, rd) for rd in rdirs]
        router = ctrl = ctrl2 = None
        out_lines, err_lines = [], []
        try:
            _wait_sockets(list(zip(procs, rdirs)))
            router = _spawn_router(rdirs)
            ctrl = _spawn_controller(
                ck, rdirs, deploy_dir, chaos="deploy/promote:kill@1"
            )
            # adopt: the fleet baseline is pinned before any candidate
            _wait_ledger(
                deploy_dir,
                lambda rs: any(r["op"] == "converged"
                               and r["ckpt"] == name_a for r in rs),
                120, f"adopt of {name_a}",
            )
            wave1 = [f"w1-{i}" for i in range(4)]
            _send_wave(router, wave1)
            _wait_done(router, out_lines, err_lines, wave1)

            name_b = _save(ck, models["b"], 1, models["config"])
            wave2 = [f"w2-{i}" for i in range(4)]
            _send_wave(router, wave2, length=20)
            # canary converts replica0, then the first promote span
            # SIGKILLs the controller (the chaos rule firing IS the
            # proof the kill landed mid-promote)
            assert ctrl.wait(timeout=240) == -9
            _wait_done(router, out_lines, err_lines, wave2)

            ctrl2 = _spawn_controller(ck, rdirs, deploy_dir)
            _wait_ledger(
                deploy_dir,
                lambda rs: any(r["op"] == "converged"
                               and r["ckpt"] == name_b for r in rs),
                240, f"resumed convergence to {name_b}",
            )
            wave3 = [f"w3-{i}" for i in range(4)]
            _send_wave(router, wave3)
            _wait_done(router, out_lines, err_lines, wave3)

            router.stdin.close()
            assert _pump(
                router, out_lines, err_lines,
                lambda: all(t[2] for t in router._pump_tails.values()),
                600,
            ), "\n".join(err_lines)[-2000:]
            router.wait(timeout=60)
            assert router.returncode == 0, "\n".join(err_lines)[-2000:]
            ctrl2.terminate()
            assert ctrl2.wait(timeout=120) == 0
            rep_errs = [_stop_replica(p)[1] for p in procs]
        finally:
            for p in (router, ctrl, ctrl2):
                if p is not None and p.poll() is None:
                    p.kill()
                    p.wait()
            for p in procs:
                if p.poll() is None:
                    p.terminate()

        # zero lost accepted requests, no dup tokens, nothing shed
        all_ids = wave1 + wave2 + wave3
        tokens = _assert_exactly_once(out_lines, all_ids)
        # the ledger tells the full story, each step exactly once
        ops = [r["op"] for r in _ledger(deploy_dir)]
        assert ops.count("canary") == 1
        assert ops.count("rollback") == 0
        promotes = [r for r in _ledger(deploy_dir)
                    if r["op"] == "promote"]
        assert [p["replica"] for p in promotes] == ["replica1"]
        # single fleet-wide digest: both acks and both live gauges on B
        for rdir in rdirs:
            ack = _ack_of(rdir)
            assert ack["pin"] == name_b and \
                ack["status"] == "committed", ack
        for i, p in enumerate(procs):
            assert p.returncode == 0, rep_errs[i][-2000:]
            assert _prom_digest(rdirs[i]) == \
                _digest_gauge_of(ck, name_b)
            # the swap recompiled nothing on either replica
            assert _decode_compile_count(rdirs[i]) == 1.0
        # bit-parity: wave1 on the old weights, wave3 on the new
        _assert_wave_parity(models, models["a"], rdirs, tokens, wave1)
        _assert_wave_parity(models, models["b"], rdirs, tokens, wave3)

    def test_canary_replica_sigkill_mid_reload_rolls_back(
        self, models, tmp_path
    ):
        """The canary SIGKILLs inside its pinned reload — before the
        candidate ever committed. The controller times out the ack,
        rolls back, pages deploy_rollback exactly once, and the
        candidate's weights never serve anywhere: every wave stays
        bit-identical to the OLD weights, in-flight work on the dead
        canary hands off to the survivor with zero loss."""
        ck = tmp_path / "ck"
        name_a = _save(ck, models["a"], 0, models["config"])
        rdirs = [tmp_path / "r0", tmp_path / "r1"]
        deploy_dir = tmp_path / "deploy"
        alerts = tmp_path / "alerts.jsonl"
        # replica0 (the canary) dies on its FIRST background reload —
        # which is the canary pin (adopt is satisfied without a reload)
        procs = [
            _spawn_pinned_replica(ck, rdirs[0],
                                  chaos="serve/reload:kill@1"),
            _spawn_pinned_replica(ck, rdirs[1]),
        ]
        router = ctrl = None
        out_lines, err_lines = [], []
        try:
            _wait_sockets(list(zip(procs, rdirs)))
            router = _spawn_router(rdirs)
            ctrl = _spawn_controller(
                ck, rdirs, deploy_dir, alerts=alerts,
                policy=_rollback_policy(tmp_path),
            )
            _wait_ledger(
                deploy_dir,
                lambda rs: any(r["op"] == "converged"
                               and r["ckpt"] == name_a for r in rs),
                120, f"adopt of {name_a}",
            )
            wave1 = [f"w1-{i}" for i in range(4)]
            _send_wave(router, wave1)
            _wait_done(router, out_lines, err_lines, wave1)

            name_b = _save(ck, models["b"], 1, models["config"])
            wave2 = [f"w2-{i}" for i in range(4)]
            _send_wave(router, wave2, length=20)
            # the canary pin lands, replica0 enters serve/reload, dies
            assert procs[0].wait(timeout=240) == -9
            _wait_done(router, out_lines, err_lines, wave2)
            recs = _wait_ledger(
                deploy_dir,
                lambda rs: any(r["op"] == "rollback" for r in rs),
                120, "rollback after canary death",
            )
            rb = [r for r in recs if r["op"] == "rollback"]
            assert rb[0]["ckpt"] == name_b and rb[0]["to"] == name_a
            assert rb[0]["reason"] == "canary_timeout"

            wave3 = [f"w3-{i}" for i in range(4)]
            _send_wave(router, wave3)
            _wait_done(router, out_lines, err_lines, wave3)

            # the condemned candidate is never retried: give the
            # controller a few more ticks, then stop it gracefully
            time.sleep(2.0)
            ctrl.terminate()
            assert ctrl.wait(timeout=120) == 0
            router.stdin.close()
            assert _pump(
                router, out_lines, err_lines,
                lambda: all(t[2] for t in router._pump_tails.values()),
                600,
            ), "\n".join(err_lines)[-2000:]
            router.wait(timeout=60)
            assert router.returncode == 0, "\n".join(err_lines)[-2000:]
            _, surv_err = _stop_replica(procs[1])
        finally:
            for p in (router, ctrl):
                if p is not None and p.poll() is None:
                    p.kill()
                    p.wait()
            for p in procs:
                if p.poll() is None:
                    p.terminate()

        all_ids = wave1 + wave2 + wave3
        tokens = _assert_exactly_once(out_lines, all_ids)
        ops = [r["op"] for r in _ledger(deploy_dir)]
        assert ops.count("canary") == 1  # condemned, not retried
        assert ops.count("rollback") == 1
        assert "promote" not in ops  # B never left the canary
        # exactly one page, with the condemned checkpoint as identity
        assert _alert_kinds(alerts) == [("deploy_rollback", name_b)]
        # the survivor stayed on A the whole time, compile-flat, and
        # the fleet's single digest is the OLD checkpoint's
        ack = _ack_of(rdirs[1])
        assert ack["pin"] == name_a and ack["status"] == "committed"
        assert procs[1].returncode == 0, surv_err[-2000:]
        assert _prom_digest(rdirs[1]) == _digest_gauge_of(ck, name_a)
        assert _decode_compile_count(rdirs[1]) == 1.0
        # B never served a token: every wave is bit-identical to A —
        # including wave2's handed-off streams from the dead canary
        _assert_wave_parity(
            models, models["a"], rdirs, tokens, all_ids
        )


@pytest.mark.slow
class TestDeployKillMatrixSlow:
    def test_controller_sigkill_mid_canary_resumes(
        self, models, tmp_path
    ):
        """Kill the controller entering the canary span — before the
        pin or its record exist. The restart replays an observed-only
        ledger and runs the whole pipeline: exactly one canary record
        total, convergence to the candidate, zero loss."""
        ck = tmp_path / "ck"
        name_a = _save(ck, models["a"], 0, models["config"])
        rdirs = [tmp_path / "r0", tmp_path / "r1"]
        deploy_dir = tmp_path / "deploy"
        procs = [_spawn_pinned_replica(ck, rd) for rd in rdirs]
        router = ctrl = ctrl2 = None
        out_lines, err_lines = [], []
        try:
            _wait_sockets(list(zip(procs, rdirs)))
            router = _spawn_router(rdirs)
            ctrl = _spawn_controller(
                ck, rdirs, deploy_dir, chaos="deploy/canary:kill@1"
            )
            _wait_ledger(
                deploy_dir,
                lambda rs: any(r["op"] == "converged"
                               and r["ckpt"] == name_a for r in rs),
                120, f"adopt of {name_a}",
            )
            wave1 = [f"w1-{i}" for i in range(4)]
            _send_wave(router, wave1)
            _wait_done(router, out_lines, err_lines, wave1)

            name_b = _save(ck, models["b"], 1, models["config"])
            wave2 = [f"w2-{i}" for i in range(4)]
            _send_wave(router, wave2, length=20)
            assert ctrl.wait(timeout=240) == -9  # died entering canary

            ctrl2 = _spawn_controller(ck, rdirs, deploy_dir)
            _wait_ledger(
                deploy_dir,
                lambda rs: any(r["op"] == "converged"
                               and r["ckpt"] == name_b for r in rs),
                240, f"resumed convergence to {name_b}",
            )
            _wait_done(router, out_lines, err_lines, wave2)
            wave3 = [f"w3-{i}" for i in range(4)]
            _send_wave(router, wave3)
            _wait_done(router, out_lines, err_lines, wave3)
            router.stdin.close()
            assert _pump(
                router, out_lines, err_lines,
                lambda: all(t[2] for t in router._pump_tails.values()),
                600,
            ), "\n".join(err_lines)[-2000:]
            router.wait(timeout=60)
            assert router.returncode == 0
            ctrl2.terminate()
            assert ctrl2.wait(timeout=120) == 0
            rep_errs = [_stop_replica(p)[1] for p in procs]
        finally:
            for p in (router, ctrl, ctrl2):
                if p is not None and p.poll() is None:
                    p.kill()
                    p.wait()
            for p in procs:
                if p.poll() is None:
                    p.terminate()

        all_ids = wave1 + wave2 + wave3
        tokens = _assert_exactly_once(out_lines, all_ids)
        ops = [r["op"] for r in _ledger(deploy_dir)]
        assert ops.count("canary") == 1
        assert ops.count("rollback") == 0
        for rdir in rdirs:
            ack = _ack_of(rdir)
            assert ack["pin"] == name_b and \
                ack["status"] == "committed"
        for i, p in enumerate(procs):
            assert p.returncode == 0, rep_errs[i][-2000:]
            assert _decode_compile_count(rdirs[i]) == 1.0
        _assert_wave_parity(models, models["a"], rdirs, tokens, wave1)
        _assert_wave_parity(models, models["b"], rdirs, tokens, wave3)

    def test_follower_replica_sigkill_mid_promote_rolls_back(
        self, models, tmp_path
    ):
        """A NON-canary replica dies inside its promote reload. The
        promote ack times out, the rollback re-pins the canary back to
        the fleet checkpoint (it had already committed the candidate),
        and the surviving fleet converges on the OLD digest."""
        ck = tmp_path / "ck"
        name_a = _save(ck, models["a"], 0, models["config"])
        rdirs = [tmp_path / "r0", tmp_path / "r1"]
        deploy_dir = tmp_path / "deploy"
        alerts = tmp_path / "alerts.jsonl"
        # replica1's FIRST reload is its promote pin — die inside it
        procs = [
            _spawn_pinned_replica(ck, rdirs[0]),
            _spawn_pinned_replica(ck, rdirs[1],
                                  chaos="serve/reload:kill@1"),
        ]
        router = ctrl = None
        out_lines, err_lines = [], []
        try:
            _wait_sockets(list(zip(procs, rdirs)))
            router = _spawn_router(rdirs)
            ctrl = _spawn_controller(
                ck, rdirs, deploy_dir, alerts=alerts,
                policy=_rollback_policy(tmp_path),
            )
            _wait_ledger(
                deploy_dir,
                lambda rs: any(r["op"] == "converged"
                               and r["ckpt"] == name_a for r in rs),
                120, f"adopt of {name_a}",
            )
            wave1 = [f"w1-{i}" for i in range(4)]
            _send_wave(router, wave1)
            _wait_done(router, out_lines, err_lines, wave1)

            name_b = _save(ck, models["b"], 1, models["config"])
            wave2 = [f"w2-{i}" for i in range(4)]
            _send_wave(router, wave2, length=20)
            # canary commits B, promote pins replica1, replica1 dies
            assert procs[1].wait(timeout=240) == -9
            _wait_done(router, out_lines, err_lines, wave2)
            recs = _wait_ledger(
                deploy_dir,
                lambda rs: any(r["op"] == "rollback" for r in rs),
                120, "rollback after follower death",
            )
            rb = [r for r in recs if r["op"] == "rollback"][0]
            assert rb["reason"] == "promote_timeout:replica1"
            # the canary swings BACK to the fleet checkpoint
            _wait_ack(rdirs[0], name_a)

            wave3 = [f"w3-{i}" for i in range(4)]
            _send_wave(router, wave3)
            _wait_done(router, out_lines, err_lines, wave3)
            ctrl.terminate()
            assert ctrl.wait(timeout=120) == 0
            router.stdin.close()
            assert _pump(
                router, out_lines, err_lines,
                lambda: all(t[2] for t in router._pump_tails.values()),
                600,
            ), "\n".join(err_lines)[-2000:]
            router.wait(timeout=60)
            assert router.returncode == 0
            _, surv_err = _stop_replica(procs[0])
        finally:
            for p in (router, ctrl):
                if p is not None and p.poll() is None:
                    p.kill()
                    p.wait()
            for p in procs:
                if p.poll() is None:
                    p.terminate()

        all_ids = wave1 + wave2 + wave3
        tokens = _assert_exactly_once(out_lines, all_ids)
        assert _alert_kinds(alerts) == [("deploy_rollback", name_b)]
        # the surviving fleet's single digest is the OLD checkpoint
        ack = _ack_of(rdirs[0])
        assert ack["pin"] == name_a and ack["status"] == "committed"
        assert procs[0].returncode == 0, surv_err[-2000:]
        assert _prom_digest(rdirs[0]) == _digest_gauge_of(ck, name_a)
        # wave1 ran on A before the deploy; wave3 on A after the
        # rollback settled. wave2 rode the canary's A->B->A swing:
        # exactly-once settlement only.
        _assert_wave_parity(models, models["a"], rdirs, tokens, wave1)
        _assert_wave_parity(models, models["a"], rdirs, tokens, wave3)
