"""Tracker backends: jsonl metrics/html/config, noop, factory gating."""

import json

from progen_tpu.tracking import (
    JsonlTracker,
    NoopTracker,
    make_tracker,
    render_sample_html,
)


class TestJsonlTracker:
    def test_metrics_and_step(self, tmp_path):
        t = JsonlTracker("proj", run_id=None, dir=str(tmp_path))
        assert t.run_id  # generated
        t.log({"loss": 1.5}, step=3)
        t.log({"loss": 1.2, "mfu": 0.4}, step=4)
        t.finish()
        rows = [
            json.loads(line)
            for line in (tmp_path / "proj" / t.run_id / "metrics.jsonl")
            .read_text()
            .splitlines()
        ]
        assert rows[0]["loss"] == 1.5 and rows[0]["_step"] == 3
        assert rows[1]["mfu"] == 0.4

    def test_resume_appends(self, tmp_path):
        t1 = JsonlTracker("p", "run1", dir=str(tmp_path))
        t1.log({"loss": 2.0}, step=1)
        t1.finish()
        t2 = JsonlTracker("p", "run1", dir=str(tmp_path))  # resume same id
        t2.log({"loss": 1.0}, step=2)
        t2.finish()
        lines = (tmp_path / "p" / "run1" / "metrics.jsonl").read_text()
        assert len(lines.splitlines()) == 2

    def test_html_and_config(self, tmp_path):
        t = JsonlTracker("p", "r", dir=str(tmp_path))
        html = render_sample_html("[tax=X] #", "MGHK")
        assert "<i>[tax=X] #</i>" in html and "MGHK" in html
        t.log_html("samples", html, step=7)
        t.set_config({"dim": 512})
        d = tmp_path / "p" / "r"
        assert (d / "samples_7.html").read_text() == html
        assert json.loads((d / "config.json").read_text())["dim"] == 512


class TestFactory:
    def test_disabled_gives_noop(self):
        # exact type: every backend subclasses NoopTracker, so isinstance
        # would pass vacuously
        assert type(make_tracker("p", disabled=True)) is NoopTracker

    def test_default_gives_jsonl_without_wandb(self, tmp_path, monkeypatch):
        import sys

        # force the ImportError branch even if wandb exists somewhere
        monkeypatch.setitem(sys.modules, "wandb", None)
        t = make_tracker("p", dir=str(tmp_path))
        assert type(t) is JsonlTracker
        t.finish()


class TestWandbTracker:
    """The real wandb is absent from the image; a mock module standing in
    for it exercises the WandbTracker code path — in particular
    resume-by-run-id, which the checkpoint Package round-trips
    (reference train.py:141-150 resume semantics)."""

    def _fake_wandb(self):
        import types

        calls = {"init": [], "log": [], "finish": 0, "config": []}

        class FakeRun:
            def __init__(self, id_):
                self.id = id_
                outer = calls

                class Cfg:
                    def update(self, d, allow_val_change=False):
                        outer["config"].append((d, allow_val_change))

                self.config = Cfg()

            def finish(self):
                calls["finish"] += 1

        mod = types.ModuleType("wandb")

        def init(project=None, id=None, resume=None):
            calls["init"].append(
                {"project": project, "id": id, "resume": resume}
            )
            return FakeRun(id or "generated-run-id")

        class Html:
            def __init__(self, html):
                self.html = html

        mod.init = init
        mod.Html = Html
        mod.log = lambda metrics, step=None: calls["log"].append(
            (metrics, step)
        )
        return mod, calls

    def test_fresh_run_and_logging(self, monkeypatch):
        import sys

        mod, calls = self._fake_wandb()
        monkeypatch.setitem(sys.modules, "wandb", mod)
        t = make_tracker("projX")
        assert type(t).__name__ == "WandbTracker"
        assert calls["init"] == [
            {"project": "projX", "id": None, "resume": None}
        ]
        assert t.run_id == "generated-run-id"
        t.log({"loss": 0.5}, step=7)
        t.log_html("samples", "<b>x</b>", step=7)
        t.set_config({"dim": 512})
        t.finish()
        assert calls["log"][0] == ({"loss": 0.5}, 7)
        html_payload = calls["log"][1][0]["samples"]
        assert html_payload.html == "<b>x</b>"
        assert calls["config"] == [({"dim": 512}, True)]
        assert calls["finish"] == 1

    def test_resume_by_run_id(self, monkeypatch):
        import sys

        mod, calls = self._fake_wandb()
        monkeypatch.setitem(sys.modules, "wandb", mod)
        t = make_tracker("projX", run_id="ckpt-run-42")
        # the resume contract: same id + resume="allow"
        assert calls["init"] == [
            {"project": "projX", "id": "ckpt-run-42", "resume": "allow"}
        ]
        assert t.run_id == "ckpt-run-42"
