"""Tracker backends: jsonl metrics/html/config, noop, factory gating."""

import json

from progen_tpu.tracking import (
    JsonlTracker,
    NoopTracker,
    make_tracker,
    render_sample_html,
)


class TestJsonlTracker:
    def test_metrics_and_step(self, tmp_path):
        t = JsonlTracker("proj", run_id=None, dir=str(tmp_path))
        assert t.run_id  # generated
        t.log({"loss": 1.5}, step=3)
        t.log({"loss": 1.2, "mfu": 0.4}, step=4)
        t.finish()
        rows = [
            json.loads(l)
            for l in (tmp_path / "proj" / t.run_id / "metrics.jsonl")
            .read_text()
            .splitlines()
        ]
        assert rows[0]["loss"] == 1.5 and rows[0]["_step"] == 3
        assert rows[1]["mfu"] == 0.4

    def test_resume_appends(self, tmp_path):
        t1 = JsonlTracker("p", "run1", dir=str(tmp_path))
        t1.log({"loss": 2.0}, step=1)
        t1.finish()
        t2 = JsonlTracker("p", "run1", dir=str(tmp_path))  # resume same id
        t2.log({"loss": 1.0}, step=2)
        t2.finish()
        lines = (tmp_path / "p" / "run1" / "metrics.jsonl").read_text()
        assert len(lines.splitlines()) == 2

    def test_html_and_config(self, tmp_path):
        t = JsonlTracker("p", "r", dir=str(tmp_path))
        html = render_sample_html("[tax=X] #", "MGHK")
        assert "<i>[tax=X] #</i>" in html and "MGHK" in html
        t.log_html("samples", html, step=7)
        t.set_config({"dim": 512})
        d = tmp_path / "p" / "r"
        assert (d / "samples_7.html").read_text() == html
        assert json.loads((d / "config.json").read_text())["dim"] == 512


class TestFactory:
    def test_disabled_gives_noop(self):
        # exact type: every backend subclasses NoopTracker, so isinstance
        # would pass vacuously
        assert type(make_tracker("p", disabled=True)) is NoopTracker

    def test_default_gives_jsonl_without_wandb(self, tmp_path, monkeypatch):
        import sys

        # force the ImportError branch even if wandb exists somewhere
        monkeypatch.setitem(sys.modules, "wandb", None)
        t = make_tracker("p", dir=str(tmp_path))
        assert type(t) is JsonlTracker
        t.finish()
