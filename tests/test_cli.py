"""CLI-level tests via click's CliRunner: the full ETL -> train -> resume
-> sample loop inside the suite (tiny config, a few seconds per stage)."""

import random

import numpy as np
import pytest
from click.testing import CliRunner

TOML = """num_tokens = 256
dim = 32
depth = 2
heads = 2
dim_head = 16
window_size = 8
seq_len = 32
global_mlp_depth = 1
ff_mult = 2
dtype = "float32"
"""

DATA_TOML = """read_from = "{fasta}"
write_to = "{out}"
num_samples = 30
max_seq_len = 28
prob_invert_seq_annotation = 0.5
fraction_valid_data = 0.2
num_sequences_per_file = 50
sort_annotations = true
"""


@pytest.fixture(scope="module")
def workspace(tmp_path_factory):
    root = tmp_path_factory.mktemp("cli")
    (root / "configs" / "model").mkdir(parents=True)
    (root / "configs" / "data").mkdir(parents=True)
    (root / "configs" / "model" / "default.toml").write_text(TOML)

    rng = random.Random(0)
    aas = "ACDEFGHIKLMNPQRSTVWY"
    fasta = root / "toy.fasta"
    with fasta.open("w") as f:
        for i in range(40):
            tax = rng.choice(["Homo sapiens", "Acinetobacter"])
            seq = "".join(rng.choice(aas) for _ in range(rng.randint(8, 24)))
            f.write(f">U{i:03d} toy n=1 Tax={tax} TaxID=1 RepID=T\n{seq}\n")
    (root / "configs" / "data" / "default.toml").write_text(
        DATA_TOML.format(fasta=fasta, out=root / "train_data")
    )
    # build train_data here so every test in this module is runnable in
    # isolation (no ordering dependency on test_full_cli_loop's ETL run —
    # that test still exercises the CLI ETL itself, idempotently)
    from progen_tpu.cli.generate_data import main as gen_main

    res = CliRunner().invoke(
        gen_main, ["--data_dir", str(root / "configs" / "data")]
    )
    assert res.exit_code == 0, res.output
    return root


def test_full_cli_loop(workspace, monkeypatch):
    monkeypatch.chdir(workspace)
    runner = CliRunner()

    from progen_tpu.cli.generate_data import main as gen_main

    res = runner.invoke(
        gen_main, ["--data_dir", str(workspace / "configs" / "data")]
    )
    assert res.exit_code == 0, res.output
    assert "tfrecord shard" in res.output

    from progen_tpu.cli.train import main as train_main

    args = [
        "--wandb_off", "--batch_size", "4", "--grad_accum_every", "1",
        "--num_steps", "2", "--validate_every", "1", "--sample_every", "100",
        "--checkpoint_every", "100", "--seq_len", "32",
        "--config_path", str(workspace / "configs" / "model"),
        "--data_path", str(workspace / "train_data"),
        "--checkpoint_path", str(workspace / "ckpts"),
    ]
    res = runner.invoke(train_main, args)
    assert res.exit_code == 0, res.output
    assert "loss:" in res.output and "valid_loss:" in res.output

    # resume: config comes from the checkpoint, training continues
    res = runner.invoke(train_main, args)
    assert res.exit_code == 0, res.output

    from progen_tpu.cli.sample import main as sample_main

    res = runner.invoke(
        sample_main,
        ["--checkpoint_path", str(workspace / "ckpts"), "--prime",
         "[tax=Homo sapiens] #", "--top_k", "5"],
    )
    assert res.exit_code == 0, res.output
    assert "params:" in res.output and "*" * 40 in res.output


def test_train_missing_config_errors(workspace, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    from progen_tpu.cli.train import main as train_main

    res = CliRunner().invoke(
        train_main, ["--config_path", str(tmp_path / "nope")]
    )
    assert res.exit_code != 0


def test_combined_features_loop(workspace, monkeypatch):
    """All round-3 features in ONE run — ring attention over a seq-sharded
    mesh, cosine LR schedule, multi-epoch, async checkpointing, KV-cache
    cadenced sampling — then a flagless resume that reconstructs the
    scheduled optimizer and mesh-independent state from the checkpoint."""
    monkeypatch.chdir(workspace)
    runner = CliRunner()

    from progen_tpu.cli.train import main as train_main

    ckpts = workspace / "ckpts_combined"
    args = [
        "--wandb_off", "--batch_size", "4", "--grad_accum_every", "1",
        "--epochs", "2", "--num_steps", "3",
        "--lr_schedule", "cosine", "--warmup_steps", "1",
        "--mesh_data", "2", "--mesh_seq", "2", "--ring_attn",
        "--async_checkpoint",
        "--validate_every", "1000", "--sample_every", "2",
        "--checkpoint_every", "1000", "--seq_len", "32",
        "--config_path", str(workspace / "configs" / "model"),
        "--data_path", str(workspace / "train_data"),
        "--checkpoint_path", str(ckpts),
    ]
    res = runner.invoke(train_main, args)
    assert res.exit_code == 0, res.output
    assert "loss:" in res.output and "sample:" in res.output

    # flagless resume: schedule + config come from the checkpoint
    res = runner.invoke(train_main, [
        "--wandb_off", "--batch_size", "4", "--grad_accum_every", "1",
        "--num_steps", "1", "--validate_every", "1000",
        "--sample_every", "1000", "--checkpoint_every", "1000",
        "--seq_len", "32",
        "--config_path", str(workspace / "configs" / "model"),
        "--data_path", str(workspace / "train_data"),
        "--checkpoint_path", str(ckpts),
    ])
    assert res.exit_code == 0, res.output
    assert "loss:" in res.output


PIPE_TOML = """num_tokens = 256
dim = 32
depth = 5
heads = 2
dim_head = 16
window_size = 8
seq_len = 32
global_mlp_depth = 1
ff_mult = 2
dtype = "float32"
scan_layers = true
"""


def test_pipeline_cli_loop(workspace, monkeypatch):
    """--mesh_pipe: the GPipe depth-sharded train path end-to-end on the
    8-virtual-device mesh (4 stages x 2 data), with validation, cadenced
    sampling off the stacked params, and a flagless pipelined resume."""
    monkeypatch.chdir(workspace)
    runner = CliRunner()

    from progen_tpu.cli.train import main as train_main

    (workspace / "configs" / "model" / "pipe.toml").write_text(PIPE_TOML)
    ckpts = workspace / "ckpts_pipe"
    args = [
        "--wandb_off", "--batch_size", "4", "--grad_accum_every", "1",
        "--num_steps", "2", "--mesh_pipe", "4", "--mesh_data", "2",
        "--pipe_microbatches", "2",
        "--model_name", "pipe",
        "--validate_every", "1", "--sample_every", "2",
        "--checkpoint_every", "1000", "--seq_len", "32",
        "--config_path", str(workspace / "configs" / "model"),
        "--data_path", str(workspace / "train_data"),
        "--checkpoint_path", str(ckpts),
    ]
    res = runner.invoke(train_main, args)
    assert res.exit_code == 0, res.output
    assert "loss:" in res.output and "valid_loss:" in res.output

    # pipelined resume restores the sharded state into the PIPELINE_RULES
    # layout (stacked layer axis over the stage axis)
    res = runner.invoke(train_main, args[:5] + ["--num_steps", "1"]
                        + args[7:])
    assert res.exit_code == 0, res.output
    assert "loss:" in res.output

    # regression: the sample CLI's params-only restore must accept a
    # checkpoint WRITTEN from a mesh-sharded train state (train on a pod,
    # sample on one host) — orbax refuses a None-sharding skeleton there
    from progen_tpu.cli.sample import main as sample_main

    res = runner.invoke(
        sample_main,
        ["--checkpoint_path", str(ckpts), "--prime",
         "[tax=Homo sapiens] #", "--top_k", "5"],
    )
    assert res.exit_code == 0, res.output
    assert "params:" in res.output


def test_pipeline_cli_1f1b(workspace, monkeypatch):
    """--pipe_schedule 1f1b composed with DP and ZeRO-1: the interleaved
    schedule end-to-end from the CLI (2 stages x 2 data, 2 microbatches,
    AdamW moments additionally sharded over the data axis)."""
    monkeypatch.chdir(workspace)
    runner = CliRunner()

    from progen_tpu.cli.train import main as train_main

    (workspace / "configs" / "model" / "pipe.toml").write_text(PIPE_TOML)
    res = runner.invoke(train_main, [
        "--wandb_off", "--batch_size", "4", "--grad_accum_every", "1",
        "--num_steps", "2", "--mesh_pipe", "2", "--mesh_data", "2",
        "--pipe_microbatches", "2", "--pipe_schedule", "1f1b", "--zero1",
        "--model_name", "pipe",
        "--validate_every", "1", "--sample_every", "1000",
        "--checkpoint_every", "1000", "--seq_len", "32",
        "--config_path", str(workspace / "configs" / "model"),
        "--data_path", str(workspace / "train_data"),
        "--checkpoint_path", str(workspace / "ckpts_pipe_1f1b"),
    ])
    assert res.exit_code == 0, res.output
    assert "loss:" in res.output and "valid_loss:" in res.output

    # row-divisibility guard: 4-row batch / 4 microbatches = 1 row per
    # microbatch, not shardable over data=2
    res = runner.invoke(train_main, [
        "--wandb_off", "--batch_size", "4", "--mesh_pipe", "2",
        "--mesh_data", "2", "--pipe_microbatches", "4",
        "--model_name", "pipe",
        "--config_path", str(workspace / "configs" / "model"),
        "--data_path", str(workspace / "train_data"),
        "--checkpoint_path", str(workspace / "ckpts_pipe_1f1b_guard"),
    ])
    assert res.exit_code != 0
    assert "PPxDP" in res.output


def test_pipeline_cli_guards(workspace, monkeypatch):
    monkeypatch.chdir(workspace)
    runner = CliRunner()

    from progen_tpu.cli.train import main as train_main

    # default.toml has no scan_layers: the stage axis needs the stacked
    # param layout, so the flag must refuse with a pointed message
    res = runner.invoke(train_main, [
        "--wandb_off", "--mesh_pipe", "2",
        "--config_path", str(workspace / "configs" / "model"),
        "--data_path", str(workspace / "train_data"),
        "--checkpoint_path", str(workspace / "ckpts_pipe_guard"),
    ])
    assert res.exit_code != 0
    assert "scan_layers" in res.output

    res = runner.invoke(train_main, [
        "--wandb_off", "--mesh_pipe", "2", "--mesh_model", "2",
        "--config_path", str(workspace / "configs" / "model"),
        "--data_path", str(workspace / "train_data"),
        "--checkpoint_path", str(workspace / "ckpts_pipe_guard"),
    ])
    assert res.exit_code != 0
    assert "mutually exclusive" in res.output


def test_eval_cli(workspace, monkeypatch):
    """Offline eval: mean per-sequence loss + perplexity over a split from
    the latest checkpoint (uses the checkpoints the train test wrote)."""
    monkeypatch.chdir(workspace)
    runner = CliRunner()

    from progen_tpu.cli.eval import main as eval_main

    if not (workspace / "ckpts").exists():  # standalone-selection safety
        from progen_tpu.cli.generate_data import main as gen_main
        from progen_tpu.cli.train import main as train_main

        if not (workspace / "train_data").exists():
            res = runner.invoke(
                gen_main, ["--data_dir", str(workspace / "configs" / "data")]
            )
            assert res.exit_code == 0, res.output

        res = runner.invoke(train_main, [
            "--wandb_off", "--batch_size", "4", "--grad_accum_every", "1",
            "--num_steps", "1", "--validate_every", "1000",
            "--sample_every", "1000", "--checkpoint_every", "1000",
            "--seq_len", "32",
            "--config_path", str(workspace / "configs" / "model"),
            "--data_path", str(workspace / "train_data"),
            "--checkpoint_path", str(workspace / "ckpts"),
        ])
        assert res.exit_code == 0, res.output

    res = runner.invoke(eval_main, [
        "--checkpoint_path", str(workspace / "ckpts"),
        "--data_path", str(workspace / "train_data"),
        "--split", "valid", "--batch_size", "4",
    ])
    assert res.exit_code == 0, res.output
    assert "perplexity:" in res.output
    loss = float(res.output.split("loss: ")[1].split()[0])
    ppl = float(res.output.split("perplexity: ")[1].split()[0])
    np.testing.assert_allclose(ppl, np.exp(loss), rtol=1e-4)


def test_train_telemetry_events(workspace, monkeypatch):
    """Acceptance for the telemetry layer: a CPU train run through the
    real CLI (JsonlTracker, not --wandb_off) leaves an events.jsonl span
    trail and a goodput record whose buckets sum to wall clock with
    >=95% attributed."""
    import json
    import sys

    monkeypatch.chdir(workspace)
    # force the JsonlTracker path deterministically: wandb unimportable
    monkeypatch.setitem(sys.modules, "wandb", None)
    runner = CliRunner()

    from progen_tpu.cli.train import main as train_main

    res = runner.invoke(train_main, [
        "--batch_size", "4", "--grad_accum_every", "1",
        "--num_steps", "2", "--validate_every", "1", "--sample_every", "100",
        "--checkpoint_every", "1", "--seq_len", "32",
        "--config_path", str(workspace / "configs" / "model"),
        "--data_path", str(workspace / "train_data"),
        "--checkpoint_path", str(workspace / "ckpts_telemetry"),
    ])
    assert res.exit_code == 0, res.output
    assert "goodput:" in res.output
    assert "step " in res.output  # step-stamped lines, not bare prints

    runs = sorted((workspace / "runs" / "progen-training").iterdir())
    assert runs, "JsonlTracker run dir missing"
    run_dir = runs[-1]

    events = [
        json.loads(line)
        for line in (run_dir / "events.jsonl").read_text().splitlines()
    ]
    spans = {r["span"] for r in events if r.get("ev") == "B"}
    assert "train/compile" in spans
    assert "ckpt/save" in spans
    # every span opened in a completed run also closed
    opened = [r["id"] for r in events if r.get("ev") == "B"]
    closed = [r["id"] for r in events if r.get("ev") == "E"]
    assert sorted(opened) == sorted(closed)

    metrics = [
        json.loads(line)
        for line in (run_dir / "metrics.jsonl").read_text().splitlines()
    ]
    goodput = [m for m in metrics if "goodput_pct" in m]
    assert goodput, "no goodput record logged"
    rep = goodput[-1]
    bucket_total = sum(
        v for k, v in rep.items() if k.startswith("bucket_s/")
    )
    assert bucket_total == pytest.approx(rep["wall_s"], rel=0.01)
    assert rep["coverage_pct"] >= 95.0


def test_train_prometheus_and_trace_export(workspace, monkeypatch):
    """Observability acceptance: a real CPU train run with --prom_file
    leaves a Prometheus textfile carrying goodput %, step-time quantiles,
    MFU and the resilience counter families, and its events.jsonl round-
    trips through `telemetry export-trace` + `summarize`."""
    import json
    import sys

    monkeypatch.chdir(workspace)
    monkeypatch.setitem(sys.modules, "wandb", None)  # JsonlTracker path
    runner = CliRunner()

    from progen_tpu.cli.train import main as train_main

    runs_root = workspace / "runs" / "progen-training"
    before = set(runs_root.iterdir()) if runs_root.exists() else set()
    prom = workspace / "train.prom"
    # 5 steps: StepTimer discards 2 warmup ticks, so step_s/mfu/tokens
    # get real post-warmup samples and the gauges land in the prom file
    res = runner.invoke(train_main, [
        "--batch_size", "4", "--grad_accum_every", "1",
        "--num_steps", "5", "--validate_every", "2", "--sample_every", "100",
        "--checkpoint_every", "100", "--seq_len", "32",
        "--config_path", str(workspace / "configs" / "model"),
        "--data_path", str(workspace / "train_data"),
        "--checkpoint_path", str(workspace / "ckpts_prom"),
        "--prom_file", str(prom),
    ])
    assert res.exit_code == 0, res.output

    text = prom.read_text()
    assert "progen_train_goodput_pct " in text
    assert 'progen_train_step_seconds{quantile="0.5"}' in text
    assert "progen_train_step_seconds_count " in text
    assert "progen_train_mfu " in text
    assert "progen_train_tokens_per_sec_per_chip " in text
    # resilience counter families are pre-declared (0 on a clean run) so
    # dashboards can rate() them before the first incident
    for fam in ("retries", "anomalies", "anomaly_rollbacks",
                "chaos_injections", "stalls", "ckpt_commit_failures"):
        assert f"# TYPE progen_train_{fam}_total counter" in text
        assert f"progen_train_{fam}_total " in text

    (new_run,) = set(runs_root.iterdir()) - before
    ev = new_run / "events.jsonl"
    assert ev.exists()

    from progen_tpu.cli.telemetry import main as telemetry_cli

    res = runner.invoke(telemetry_cli, ["export-trace", str(ev)])
    assert res.exit_code == 0, res.output
    trace = json.loads((new_run / "trace.json").read_text())
    names = {e["name"] for e in trace["traceEvents"] if e["ph"] == "C"}
    assert "step_ms" in names  # metrics.jsonl picked up as sibling
    assert "goodput_pct" in names  # end-of-run goodput_host record
    spans = {e["name"] for e in trace["traceEvents"] if e["ph"] == "B"}
    assert "train/compile" in spans and "ckpt/save" in spans

    res = runner.invoke(telemetry_cli, ["summarize", str(ev)])
    assert res.exit_code == 0, res.output
    assert "goodput (per host)" in res.output
    assert "span latency" in res.output
