"""Alert router (telemetry/alert_router.py): fingerprints, dedup
across repeats, severity mapping, per-route silence windows and rate
limits, webhook retry/backoff honoring PROGEN_RETRY_*, the
notifications ledger, and restart state reload — plus the AlertSink
persistence fix (no re-fire after a collector restart). Jax-free;
webhook targets are an in-process stdlib HTTP server."""

import json
import tempfile
from pathlib import Path

import pytest

from progen_tpu.telemetry.alert_router import (
    AlertRouter,
    RouteSpec,
    fingerprint,
    load_router_config,
    read_notifications,
)
from progen_tpu.telemetry.alerts import AlertSink
from tests.test_remote_write import _Receiver


def _alert(kind="staleness", state="stale", source="r0",
           objective="", ts=100.0):
    # the sink builds real records; tests route through it so the
    # PGL006 ownership contract holds in the test corpus too
    with tempfile.TemporaryDirectory() as d:
        sink = AlertSink(Path(d) / "alerts.jsonl")
        if kind == "staleness":
            rec = sink.staleness(source, up=(state == "fresh"),
                                 age_s=0.0, now=ts)
        else:
            rec = sink.slo_transition(
                {"objective": objective, "state": state, "ts": ts}
            )
        sink.close()
    return rec


def _router(tmp_path, routes, **kw):
    return AlertRouter(tmp_path / "notifications.jsonl", routes, **kw)


@pytest.fixture()
def receiver():
    r = _Receiver()
    yield r
    r.close()


class TestFingerprint:
    def test_stable_identity(self):
        a = _alert(ts=1.0)
        b = _alert(ts=999.0, state="fresh")
        assert fingerprint(a) == fingerprint(b) == "staleness:r0:"
        assert fingerprint(_alert(kind="slo_burn", source="fleet",
                                  objective="ttft_p95")) \
            == "slo_burn:fleet:ttft_p95"


class TestConfig:
    def test_shipped_example_parses(self):
        sev, routes = load_router_config(
            "configs/serving/alert_router.toml"
        )
        assert {r.name for r in routes} == {"ledger", "chat", "pager"}
        assert sev["stale"] == "critical"

    def test_unknown_route_key_raises(self, tmp_path):
        p = tmp_path / "r.toml"
        p.write_text('[route_x]\nsink = "file"\nsilences = 1.0\n')
        with pytest.raises(ValueError, match="silences"):
            load_router_config(p)

    def test_unknown_table_raises(self, tmp_path):
        p = tmp_path / "r.toml"
        p.write_text('[routes_x]\nsink = "file"\n')
        with pytest.raises(ValueError, match="routes_x"):
            load_router_config(p)

    def test_severity_override_and_bad_values(self, tmp_path):
        p = tmp_path / "r.toml"
        p.write_text(
            '[alert_router]\nseverity_stale = "warning"\n'
            '[route_x]\nsink = "file"\n'
        )
        sev, _ = load_router_config(p)
        assert sev["stale"] == "warning"
        p.write_text('[alert_router]\nseverity_stale = "mega"\n'
                     '[route_x]\nsink = "file"\n')
        with pytest.raises(ValueError, match="mega"):
            load_router_config(p)

    def test_webhook_requires_url(self):
        with pytest.raises(ValueError, match="url"):
            RouteSpec(name="w", sink="webhook")

    def test_no_routes_raises(self, tmp_path):
        p = tmp_path / "r.toml"
        p.write_text("[alert_router]\n")
        with pytest.raises(ValueError, match="route"):
            load_router_config(p)


class TestPipeline:
    def test_dedup_across_repeats(self, tmp_path):
        router = _router(tmp_path, [RouteSpec(name="ops")])
        first = router.handle(_alert(ts=1.0))
        assert [n["status"] for n in first] == ["sent"]
        repeat = router.handle(_alert(ts=2.0))
        assert [n["status"] for n in repeat] == ["deduped"]
        assert repeat[0]["route"] == ""
        # a STATE CHANGE is a new edge, not a repeat
        recovery = router.handle(_alert(ts=3.0, state="fresh"))
        assert [n["status"] for n in recovery] == ["sent"]
        router.close()

    def test_min_severity_floor(self, tmp_path):
        router = _router(tmp_path, [
            RouteSpec(name="all", min_severity="info"),
            RouteSpec(name="page", min_severity="critical"),
        ])
        notes = router.handle(
            _alert(kind="slo_burn", source="fleet",
                   objective="o", state="warn")
        )
        assert [(n["route"], n["status"]) for n in notes] == \
            [("all", "sent")]
        notes = router.handle(
            _alert(kind="slo_burn", source="fleet",
                   objective="o", state="burning", ts=2.0)
        )
        assert {(n["route"], n["status"]) for n in notes} == \
            {("all", "sent"), ("page", "sent")}
        router.close()

    def test_kind_filter(self, tmp_path):
        router = _router(tmp_path, [
            RouteSpec(name="slo_only", kinds="slo_burn"),
        ])
        assert router.handle(_alert()) == []
        assert router.counts["sent"] == 0
        router.close()

    def test_silence_window_per_fingerprint(self, tmp_path):
        router = _router(tmp_path, [
            RouteSpec(name="fast"),
            RouteSpec(name="quiet", silence_s=100.0),
        ])
        router.handle(_alert(ts=10.0, state="stale"))
        notes = router.handle(_alert(ts=20.0, state="fresh"))
        by_route = {n["route"]: n for n in notes}
        assert by_route["fast"]["status"] == "sent"
        assert by_route["quiet"]["status"] == "silenced"
        assert by_route["quiet"]["reason"] == "silence_window"
        # past the window the route wakes up again
        notes = router.handle(_alert(ts=150.0, state="stale"))
        assert {n["status"] for n in notes} == {"sent"}
        # a DIFFERENT fingerprint is never silenced by this one
        notes = router.handle(_alert(ts=151.0, source="r1"))
        assert {n["status"] for n in notes} == {"sent"}
        router.close()

    def test_rate_limit(self, tmp_path):
        router = _router(tmp_path, [
            RouteSpec(name="ops", rate_limit_per_min=2.0),
        ])
        for i, src in enumerate(("a", "b", "c")):
            notes = router.handle(_alert(source=src, ts=10.0 + i))
            assert len(notes) == 1
        statuses = [
            n["status"]
            for n in read_notifications(tmp_path / "notifications.jsonl")
        ]
        assert statuses == ["sent", "sent", "silenced"]
        # a minute later the budget refills
        notes = router.handle(_alert(source="d", ts=200.0))
        assert notes[0]["status"] == "sent"
        router.close()

    def test_stderr_sink(self, tmp_path, capsys):
        router = _router(tmp_path, [RouteSpec(name="term",
                                              sink="stderr")])
        router.handle(_alert())
        assert "staleness:r0:" in capsys.readouterr().err
        router.close()

    def test_handle_never_raises(self, tmp_path, capsys):
        router = _router(tmp_path, [RouteSpec(name="ops")])
        assert router.handle(None) == []  # not even on garbage
        assert "dropped alert" in capsys.readouterr().err
        router.close()


class TestWebhook:
    def test_post_delivers_alert_body(self, tmp_path, receiver):
        router = _router(tmp_path, [
            RouteSpec(name="hook", sink="webhook", url=receiver.url),
        ])
        notes = router.handle(_alert())
        assert notes[0]["status"] == "sent"
        body = json.loads(receiver.bodies[0])
        assert body["fingerprint"] == "staleness:r0:"
        assert body["severity"] == "critical"
        assert body["alert"]["state"] == "stale"
        router.close()

    def test_retry_honors_env_and_recovers(self, tmp_path, receiver,
                                           monkeypatch):
        monkeypatch.setenv("PROGEN_RETRY_ATTEMPTS", "3")
        monkeypatch.setenv("PROGEN_RETRY_BASE_S", "0.01")
        monkeypatch.setenv("PROGEN_RETRY_MAX_S", "0.02")
        receiver.fail_next = 2  # two 503s, then accept
        router = _router(tmp_path, [
            RouteSpec(name="hook", sink="webhook", url=receiver.url),
        ])
        notes = router.handle(_alert())
        assert notes[0]["status"] == "sent"
        assert len(receiver.bodies) == 1
        router.close()

    def test_attempts_budget_exhausted_is_failed(self, tmp_path,
                                                 receiver, monkeypatch):
        monkeypatch.setenv("PROGEN_RETRY_ATTEMPTS", "2")
        monkeypatch.setenv("PROGEN_RETRY_BASE_S", "0.01")
        monkeypatch.setenv("PROGEN_RETRY_MAX_S", "0.02")
        receiver.fail_next = 5
        router = _router(tmp_path, [
            RouteSpec(name="hook", sink="webhook", url=receiver.url),
        ])
        notes = router.handle(_alert())
        assert notes[0]["status"] == "failed"
        assert notes[0]["reason"]
        assert receiver.fail_next == 3  # exactly 2 attempts spent
        router.close()


class TestRestartReload:
    def test_ledger_reload_keeps_dedup(self, tmp_path):
        router = _router(tmp_path, [RouteSpec(name="ops")])
        router.handle(_alert(ts=1.0))
        router.close()
        # a NEW router over the same ledger: the repeat stays deduped
        router2 = _router(tmp_path, [RouteSpec(name="ops")])
        notes = router2.handle(_alert(ts=2.0))
        assert [n["status"] for n in notes] == ["deduped"]
        assert router2.counts["sent"] == 1  # reloaded history counts
        router2.close()

    def test_ledger_reload_keeps_silence(self, tmp_path):
        routes = [RouteSpec(name="quiet", silence_s=100.0)]
        router = _router(tmp_path, routes)
        router.handle(_alert(ts=10.0))
        router.close()
        router2 = _router(tmp_path, routes)
        notes = router2.handle(_alert(ts=20.0, state="fresh"))
        assert [n["status"] for n in notes] == ["silenced"]
        router2.close()


class TestDeployRollbackRouting:
    def test_rollback_alert_routes_as_critical(self, tmp_path):
        sink = AlertSink(tmp_path / "alerts.jsonl")
        rec = sink.deploy_rollback(
            "ckpt_000001", "ppl_regression:10.2>10.1", now=5.0
        )
        sink.close()
        assert rec is not None and rec["kind"] == "deploy_rollback"
        router = _router(tmp_path, [
            RouteSpec(name="page", min_severity="critical"),
        ])
        notes = router.handle(rec)
        assert [(n["route"], n["status"]) for n in notes] == \
            [("page", "sent")]
        assert notes[0]["severity"] == "critical"
        assert notes[0]["fingerprint"] == \
            "deploy_rollback:deploy:ckpt_000001"
        router.close()

    def test_same_checkpoint_rollback_is_exactly_once(self, tmp_path):
        """The controller replays its ledger on restart and re-fires
        every recorded rollback into the sink — the sink's state dedup
        is what keeps the webhook at one page per checkpoint."""
        sink = AlertSink(tmp_path / "alerts.jsonl")
        assert sink.deploy_rollback("ckpt_000001", "canary_timeout",
                                    now=1.0) is not None
        sink.close()
        sink2 = AlertSink(tmp_path / "alerts.jsonl")
        assert sink2.deploy_rollback("ckpt_000001", "canary_timeout",
                                     now=2.0) is None
        assert sink2.suppressed == 1
        # a DIFFERENT condemned checkpoint is a fresh page
        assert sink2.deploy_rollback("ckpt_000002", "probe_failed",
                                     now=3.0) is not None
        sink2.close()


class TestEscalation:
    """Unacked pages climb the chain: a warning+ alert sent through a
    route with ``escalate_to`` re-fires through the target after
    ``escalate_after_s`` unless a state change acked it first."""

    CHAIN = [
        # pager only takes slo_burn normally — so a staleness record
        # reaching it proves the escalation bypassed the kind gate
        RouteSpec(name="chat", escalate_to="pager",
                  escalate_after_s=60.0),
        RouteSpec(name="pager", kinds="slo_burn"),
    ]

    def test_spec_validation(self):
        with pytest.raises(ValueError, match="set together"):
            RouteSpec(name="x", escalate_to="y")
        with pytest.raises(ValueError, match="set together"):
            RouteSpec(name="x", escalate_after_s=5.0)
        with pytest.raises(ValueError, match="itself"):
            RouteSpec(name="x", escalate_to="x", escalate_after_s=5.0)

    def test_unknown_target_raises(self, tmp_path):
        with pytest.raises(ValueError, match="unknown"):
            _router(tmp_path, [
                RouteSpec(name="chat", escalate_to="nobody",
                          escalate_after_s=5.0),
            ])

    def test_toml_keys_parse(self, tmp_path):
        p = tmp_path / "r.toml"
        p.write_text(
            '[route_chat]\nsink = "file"\n'
            'escalate_to = "pager"\nescalate_after_s = 300.0\n'
            '[route_pager]\nsink = "file"\n'
        )
        _, routes = load_router_config(p)
        by_name = {r.name: r for r in routes}
        assert by_name["chat"].escalate_to == "pager"
        assert by_name["chat"].escalate_after_s == 300.0

    def test_fires_after_deadline_bypassing_target_gates(
        self, tmp_path
    ):
        router = _router(tmp_path, self.CHAIN)
        notes = router.handle(_alert(ts=10.0))
        # normal delivery: chat only (pager's kind filter skips it)
        assert [(n["route"], n["status"]) for n in notes] == \
            [("chat", "sent")]
        assert router.tick(now=30.0) == []  # not due yet
        fired = router.tick(now=71.0)
        assert [(n["route"], n["status"]) for n in fired] == \
            [("pager", "escalated")]
        assert fired[0]["reason"] == "escalated_from:chat"
        assert fired[0]["fingerprint"] == "staleness:r0:"
        assert router.counts["escalated"] == 1
        # one-shot: the chain does not re-fire
        assert router.tick(now=999.0) == []
        router.close()

    def test_state_change_disarms(self, tmp_path):
        router = _router(tmp_path, self.CHAIN)
        router.handle(_alert(ts=10.0))
        # recovery before the deadline acks the page
        router.handle(_alert(ts=20.0, state="fresh"))
        assert router.tick(now=999.0) == []
        assert router.counts["escalated"] == 0
        router.close()

    def test_info_severity_never_arms(self, tmp_path):
        router = _router(tmp_path, self.CHAIN)
        # a recovery edge is info-level: sent, but never escalation
        # material (the chain exists for unacked PROBLEMS)
        notes = router.handle(_alert(ts=10.0, state="fresh"))
        assert [n["status"] for n in notes] == ["sent"]
        assert router.tick(now=999.0) == []
        router.close()

    def test_escalated_delivery_does_not_cascade(self, tmp_path):
        """pager's own escalate_to must not arm off an escalated
        delivery — chains are one hop per edge, not loops."""
        chain = [
            RouteSpec(name="chat", escalate_to="pager",
                      escalate_after_s=60.0),
            RouteSpec(name="pager", kinds="slo_burn",
                      escalate_to="chat", escalate_after_s=60.0),
        ]
        router = _router(tmp_path, chain)
        router.handle(_alert(ts=10.0))
        assert len(router.tick(now=71.0)) == 1
        assert router.tick(now=9999.0) == []
        router.close()

    def test_pending_escalation_survives_restart(self, tmp_path):
        router = _router(tmp_path, self.CHAIN)
        router.handle(_alert(ts=10.0))
        router.close()  # "crash" with the chain armed

        router2 = _router(tmp_path, self.CHAIN)
        fired = router2.tick(now=71.0)
        assert [(n["route"], n["status"]) for n in fired] == \
            [("pager", "escalated")]
        router2.close()

    def test_fired_escalation_not_replayed(self, tmp_path):
        router = _router(tmp_path, self.CHAIN)
        router.handle(_alert(ts=10.0))
        assert len(router.tick(now=71.0)) == 1
        router.close()
        # the escalated record is on the ledger: a restart must not
        # page again off the same edge
        router2 = _router(tmp_path, self.CHAIN)
        assert router2.tick(now=9999.0) == []
        assert router2.counts["escalated"] == 1  # history, not re-fire
        router2.close()

    def test_resolved_edge_disarms_across_restart(self, tmp_path):
        router = _router(tmp_path, self.CHAIN)
        router.handle(_alert(ts=10.0))
        router.handle(_alert(ts=20.0, state="fresh"))
        router.close()
        router2 = _router(tmp_path, self.CHAIN)
        assert router2.tick(now=9999.0) == []
        router2.close()

    def test_escalation_to_webhook(self, tmp_path, receiver):
        router = _router(tmp_path, [
            RouteSpec(name="chat", escalate_to="hook",
                      escalate_after_s=30.0),
            RouteSpec(name="hook", sink="webhook", url=receiver.url,
                      kinds="slo_burn"),
        ])
        router.handle(_alert(ts=10.0))
        assert receiver.bodies == []  # kind gate held the normal path
        fired = router.tick(now=41.0)
        assert [n["status"] for n in fired] == ["escalated"]
        body = json.loads(receiver.bodies[0])
        assert body["alert"]["kind"] == "staleness"
        router.close()


class TestAlertSinkPersistence:
    def test_no_refire_after_restart(self, tmp_path):
        sink = AlertSink(tmp_path / "alerts.jsonl")
        assert sink.staleness("r0", up=False, age_s=30.0,
                              now=1.0) is not None
        sink.close()
        # restart: same path, state reloaded from disk
        sink2 = AlertSink(tmp_path / "alerts.jsonl")
        assert sink2.last_state("staleness", "r0") == "stale"
        assert sink2.staleness("r0", up=False, age_s=60.0,
                               now=2.0) is None
        assert sink2.suppressed == 1
        # the RECOVERY edge still fires
        assert sink2.staleness("r0", up=True, age_s=0.0,
                               now=3.0) is not None
        sink2.close()
        lines = [
            json.loads(ln) for ln in
            (tmp_path / "alerts.jsonl").read_text().splitlines()
        ]
        assert [r["state"] for r in lines] == ["stale", "fresh"]

    def test_slo_state_persists(self, tmp_path):
        sink = AlertSink(tmp_path / "alerts.jsonl")
        sink.slo_transition({"objective": "ttft_p95",
                             "state": "burning", "ts": 1.0})
        sink.close()
        sink2 = AlertSink(tmp_path / "alerts.jsonl")
        assert sink2.last_states("slo_burn") == {"ttft_p95": "burning"}
        assert sink2.slo_transition(
            {"objective": "ttft_p95", "state": "burning", "ts": 2.0}
        ) is None
        assert sink2.slo_transition(
            {"objective": "ttft_p95", "state": "resolved", "ts": 3.0}
        ) is not None
        sink2.close()

    def test_relay_sees_only_deduped_stream(self, tmp_path):
        seen = []
        sink = AlertSink(tmp_path / "alerts.jsonl", relay=seen.append)
        sink.staleness("r0", up=False, age_s=30.0, now=1.0)
        sink.close()
        sink2 = AlertSink(tmp_path / "alerts.jsonl", relay=seen.append)
        sink2.staleness("r0", up=False, age_s=60.0, now=2.0)  # repeat
        sink2.staleness("r0", up=True, age_s=0.0, now=3.0)
        sink2.close()
        assert [(r["state"]) for r in seen] == ["stale", "fresh"]
