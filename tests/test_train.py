"""Training-stack tests: loss semantics, grad accumulation, pjit==single.

The pjit test is the SURVEY §4 recommendation: run the real sharded train
step on the 8-virtual-CPU-device mesh and assert bit-comparable results with
the single-device step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from progen_tpu.config import ProGenConfig
from progen_tpu.models.progen import ProGen
from progen_tpu.parallel.partition import make_mesh
from progen_tpu.training.loss import cross_entropy, eos_loss_mask
from progen_tpu.training.optimizer import make_optimizer, weight_decay_mask
from progen_tpu.training.step import (
    compile_train_step,
    init_train_state,
    make_eval_step,
    make_train_step,
)

TINY = ProGenConfig(
    num_tokens=32,
    dim=32,
    seq_len=32,
    depth=3,
    window_size=8,
    global_mlp_depth=1,
    heads=2,
    dim_head=16,
    ff_mult=2,
    dtype="float32",
)

# the bit-parity assertions below hold on the jax>=0.7 runtimes this repo
# targets; the older GSPMD partitioner reassociates reductions differently
# on the host-platform CPU mesh (0.6% loss drift — far past any honest
# tolerance), so the parity claim is unverifiable there, not merely loose
_gspmd_parity_skip = pytest.mark.skipif(
    not hasattr(jax.lax, "pcast"),
    reason="pre-0.7 GSPMD on the virtual-CPU mesh diverges numerically "
    "from the single-device step; parity is asserted on target runtimes",
)


def synthetic_batch(key, shape, vocab=32):
    """Token sequences with trailing padding, so the EOS mask matters."""
    ids = jax.random.randint(key, shape, 1, vocab)
    lengths = jax.random.randint(
        jax.random.fold_in(key, 1), shape[:-1] + (1,), shape[-1] // 2, shape[-1]
    )
    pos = jnp.arange(shape[-1])
    return jnp.where(pos < lengths, ids, 0)


class TestCrossEntropy:
    def test_mask_keeps_first_pad_only(self):
        targets = jnp.array([[5, 3, 0, 0, 0]])
        mask = eos_loss_mask(targets)
        np.testing.assert_array_equal(
            mask[0], jnp.array([True, True, True, False, False])
        )

    def test_no_padding_full_mask(self):
        targets = jnp.array([[5, 3, 2, 7]])
        np.testing.assert_array_equal(eos_loss_mask(targets)[0], jnp.ones(4, bool))

    def test_matches_reference_formula(self):
        """Hand-rolled reference semantics (utils.py:45-59), per sequence."""
        key = jax.random.PRNGKey(0)
        logits = jax.random.normal(key, (2, 6, 8))
        targets = jnp.array([[3, 1, 4, 0, 0, 0], [2, 2, 2, 2, 2, 2]])
        out = cross_entropy(logits, targets)

        logprobs = jax.nn.log_softmax(logits, axis=-1)
        for b in range(2):
            nll = -np.take_along_axis(
                np.asarray(logprobs[b]), np.asarray(targets[b])[:, None], axis=-1
            )[:, 0]
            t = np.asarray(targets[b])
            mask = t != 0
            eos = (~mask).cumsum(-1) == 1
            m = mask | eos
            expected = (nll * m).sum() / m.sum()
            np.testing.assert_allclose(out[b], expected, rtol=1e-6)

    def test_f32_even_for_bf16_logits(self):
        logits = jnp.ones((1, 4, 8), jnp.bfloat16)
        targets = jnp.ones((1, 4), jnp.int32)
        assert cross_entropy(logits, targets).dtype == jnp.float32


class TestWeightDecayMask:
    def test_matrices_only(self):
        params = {"w": jnp.ones((3, 3)), "b": jnp.ones((3,)), "s": jnp.ones(())}
        mask = weight_decay_mask(params)
        assert mask["w"] and not mask["b"] and not mask["s"]


@pytest.fixture(scope="module")
def tiny_setup():
    model = ProGen(TINY)
    optimizer = make_optimizer(learning_rate=1e-3)
    state, _ = init_train_state(
        model, optimizer, jax.random.PRNGKey(0), TINY.seq_len
    )
    return model, optimizer, state


class TestTrainStep:
    def test_loss_decreases(self, tiny_setup):
        model, optimizer, _ = tiny_setup
        # fresh state: the donated argument must not alias the shared fixture
        state, _ = init_train_state(
            model, optimizer, jax.random.PRNGKey(0), TINY.seq_len
        )
        step = jax.jit(make_train_step(model, optimizer), donate_argnums=0)
        batch = synthetic_batch(jax.random.PRNGKey(1), (4, TINY.seq_len + 1))[
            None
        ]  # (1, 4, L+1)
        losses = []
        for _ in range(30):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0] * 0.7, losses

    def test_grad_accum_equivalence(self, tiny_setup):
        """(1, 4, L) in one micro-batch == (2, 2, L) accumulated, since both
        average per-micro means of equal size."""
        model, optimizer, _ = tiny_setup
        step = jax.jit(make_train_step(model, optimizer))
        data = synthetic_batch(jax.random.PRNGKey(2), (4, TINY.seq_len + 1))

        def fresh():
            s, _ = init_train_state(
                model, optimizer, jax.random.PRNGKey(0), TINY.seq_len
            )
            return s

        s1, m1 = step(fresh(), data[None])
        s2, m2 = step(fresh(), data.reshape(2, 2, TINY.seq_len + 1))
        np.testing.assert_allclose(m1["loss"], m2["loss"], rtol=1e-6)
        leaves1 = jax.tree.leaves(s1.params)
        leaves2 = jax.tree.leaves(s2.params)
        for a, b in zip(leaves1, leaves2):
            np.testing.assert_allclose(a, b, atol=1e-6)

    def test_eval_step_matches_train_loss(self, tiny_setup):
        model, optimizer, state = tiny_setup
        data = synthetic_batch(jax.random.PRNGKey(3), (4, TINY.seq_len + 1))
        train = jax.jit(make_train_step(model, optimizer))
        ev = jax.jit(make_eval_step(model))
        _, metrics = train(state, data[None])
        np.testing.assert_allclose(
            float(ev(state, data)), float(metrics["loss"]), rtol=1e-6
        )


class TestPjitParity:
    pytestmark = _gspmd_parity_skip

    def test_seq_parallel_step_matches_single_device(self):
        """Sequence parallelism = mesh seq axis: shard activations' sequence
        dim + SGU spatial rows over 4 devices; results must equal the
        single-device step."""
        model = ProGen(TINY)
        optimizer = make_optimizer(learning_rate=1e-3)
        data = synthetic_batch(jax.random.PRNGKey(11), (4, TINY.seq_len + 1))
        batch = data[None]

        s_single, _ = init_train_state(
            model, optimizer, jax.random.PRNGKey(0), TINY.seq_len
        )
        s_single, m_single = jax.jit(make_train_step(model, optimizer))(
            s_single, batch
        )

        mesh = make_mesh(data=2, seq=4, model=1)
        s_mesh, shardings = init_train_state(
            model, optimizer, jax.random.PRNGKey(0), TINY.seq_len, mesh=mesh
        )
        step_mesh = compile_train_step(
            model, optimizer, s_mesh, shardings, mesh
        )
        with mesh:
            s_mesh, m_mesh = step_mesh(s_mesh, batch)
        np.testing.assert_allclose(
            float(m_mesh["loss"]), float(m_single["loss"]), rtol=1e-5
        )
        for a, b in zip(
            jax.tree.leaves(s_single.params),
            jax.tree.leaves(jax.device_get(s_mesh.params)),
        ):
            np.testing.assert_allclose(a, b, atol=2e-5)

    def test_sharded_step_matches_single_device(self):
        """The full sharded train step on a (2, 1, 4) mesh must reproduce the
        single-device step: same loss, same updated params."""
        model = ProGen(TINY)
        optimizer = make_optimizer(learning_rate=1e-3)
        data = synthetic_batch(jax.random.PRNGKey(7), (8, TINY.seq_len + 1))
        batch = data[None]  # (1, 8, L+1)

        # single device
        s_single, _ = init_train_state(
            model, optimizer, jax.random.PRNGKey(0), TINY.seq_len
        )
        step_single = jax.jit(make_train_step(model, optimizer))
        s_single, m_single = step_single(s_single, batch)

        # sharded: data=2 x model=4
        mesh = make_mesh(data=2, seq=1, model=4)
        s_mesh, shardings = init_train_state(
            model, optimizer, jax.random.PRNGKey(0), TINY.seq_len, mesh=mesh
        )
        step_mesh = compile_train_step(
            model, optimizer, s_mesh, shardings, mesh
        )
        with mesh:
            s_mesh, m_mesh = step_mesh(s_mesh, batch)

        np.testing.assert_allclose(
            float(m_mesh["loss"]), float(m_single["loss"]), rtol=1e-5
        )
        single_leaves = jax.tree.leaves(s_single.params)
        mesh_leaves = jax.tree.leaves(jax.device_get(s_mesh.params))
        for a, b in zip(single_leaves, mesh_leaves):
            np.testing.assert_allclose(a, b, atol=2e-5)


class TestBlockedSguParity:
    pytestmark = _gspmd_parity_skip

    def test_blocked_sgu_seq_parallel_matches_single_device(self):
        """The long8k recipe combination — block-triangular SGU mix on a
        sequence-parallel mesh — must reproduce the single-device dense-SGU
        step (same math twice reassociated: blocked mix + GSPMD sharding of
        the sliced spatial weights)."""
        import dataclasses

        cfg = dataclasses.replace(TINY, sgu_block_size=8)  # 32 -> 16 -> 8
        model_b = ProGen(cfg)
        model_d = ProGen(TINY)
        optimizer = make_optimizer(learning_rate=1e-3)
        data = synthetic_batch(jax.random.PRNGKey(13), (4, TINY.seq_len + 1))
        batch = data[None]

        s_single, _ = init_train_state(
            model_d, optimizer, jax.random.PRNGKey(0), TINY.seq_len
        )
        s_single, m_single = jax.jit(make_train_step(model_d, optimizer))(
            s_single, batch
        )

        mesh = make_mesh(data=2, seq=4, model=1)
        s_mesh, shardings = init_train_state(
            model_b, optimizer, jax.random.PRNGKey(0), TINY.seq_len,
            mesh=mesh,
        )
        step_mesh = compile_train_step(
            model_b, optimizer, s_mesh, shardings, mesh
        )
        with mesh:
            s_mesh, m_mesh = step_mesh(s_mesh, batch)
        np.testing.assert_allclose(
            float(m_mesh["loss"]), float(m_single["loss"]), rtol=1e-5
        )
        for a, b in zip(
            jax.tree.leaves(s_single.params),
            jax.tree.leaves(jax.device_get(s_mesh.params)),
        ):
            np.testing.assert_allclose(a, b, atol=2e-5)


class TestLrSchedule:
    def test_cosine_schedule_shape(self):
        from progen_tpu.training.optimizer import _make_schedule

        sched = _make_schedule(1e-3, "cosine", warmup_steps=10,
                               total_steps=100)
        assert float(sched(0)) == 0.0
        np.testing.assert_allclose(float(sched(10)), 1e-3, rtol=1e-6)
        # decays to the 10% floor at the horizon
        np.testing.assert_allclose(float(sched(100)), 1e-4, rtol=1e-5)
        assert float(sched(55)) < 1e-3

    def test_constant_is_reference_parity(self):
        from progen_tpu.training.optimizer import _make_schedule

        assert _make_schedule(2e-4, "constant", 0, 0) == 2e-4

    def test_bad_schedule_raises(self):
        from progen_tpu.training.optimizer import make_optimizer

        with pytest.raises(ValueError, match="unknown schedule"):
            make_optimizer(schedule="nope")
        with pytest.raises(ValueError, match="total_steps"):
            make_optimizer(schedule="cosine", warmup_steps=5, total_steps=5)

    def test_scheduled_optimizer_trains(self):
        from progen_tpu.training.optimizer import make_optimizer
        from progen_tpu.training.step import make_train_step

        model = ProGen(TINY)
        optimizer = make_optimizer(
            1e-3, schedule="cosine", warmup_steps=1, total_steps=4
        )
        state, _ = init_train_state(
            model, optimizer, jax.random.PRNGKey(0), TINY.seq_len
        )
        step = jax.jit(make_train_step(model, optimizer))
        batch = synthetic_batch(
            jax.random.PRNGKey(2), (2, TINY.seq_len + 1)
        )[None]
        p0 = jax.tree.leaves(state.params)[0]
        for _ in range(3):
            state, metrics = step(state, batch)
        assert np.isfinite(float(metrics["loss"]))
        # warmup step 0 has lr 0: params must still change by step 3
        assert not np.allclose(
            np.asarray(p0), np.asarray(jax.tree.leaves(state.params)[0])
        )


class TestZero1:
    """ZeRO-1 optimizer-state sharding (partition.zero1_opt_shardings):
    moments shard over ``data``, params keep their layout, and the training
    trajectory is unchanged."""

    def _steps(self, zero1, n=3):
        model = ProGen(TINY)
        optimizer = make_optimizer(learning_rate=1e-3)
        mesh = make_mesh(data=4, seq=1, model=2)
        state, shardings = init_train_state(
            model, optimizer, jax.random.PRNGKey(0), TINY.seq_len,
            mesh=mesh, zero1=zero1,
        )
        step = compile_train_step(model, optimizer, state, shardings, mesh)
        with mesh:
            for i in range(n):
                batch = synthetic_batch(
                    jax.random.PRNGKey(100 + i), (8, TINY.seq_len + 1)
                )[None]
                state, metrics = step(state, batch)
        return state, shardings, mesh, metrics

    def test_trajectory_matches_baseline(self):
        s0, _, _, m0 = self._steps(zero1=False)
        s1, _, _, m1 = self._steps(zero1=True)
        np.testing.assert_allclose(
            float(m1["loss"]), float(m0["loss"]), rtol=1e-6
        )
        for a, b in zip(
            jax.tree.leaves(jax.device_get(s0.params)),
            jax.tree.leaves(jax.device_get(s1.params)),
        ):
            np.testing.assert_allclose(a, b, atol=1e-6)

    def test_moments_sharded_params_not(self):
        """Per-device optimizer-moment bytes shrink ~1/data vs the base
        layout (exact factor depends on the few leaves with no free
        divisible dim, e.g. model-sharded biases); params keep a
        data-replicated layout; every 2-D moment with a free divisible dim
        carries 'data' in its spec."""
        s_base, *_ = self._steps(zero1=False, n=1)
        s_z1, _, mesh, _ = self._steps(zero1=True, n=1)
        data_size = mesh.shape["data"]

        def device_bytes(tree):
            return sum(
                leaf.addressable_shards[0].data.size * leaf.dtype.itemsize
                for leaf in jax.tree.leaves(tree)
                if hasattr(leaf, "addressable_shards")
            )

        base_b, z1_b = device_bytes(s_base.opt_state), device_bytes(
            s_z1.opt_state
        )
        # kernels dominate; allow slack for unupgradeable small leaves
        assert z1_b <= base_b / data_size * 1.5, (base_b, z1_b)

        for leaf in jax.tree.leaves(s_z1.opt_state):
            if getattr(leaf, "ndim", 0) == 2:
                spec = list(leaf.sharding.spec) + [None] * (
                    2 - len(leaf.sharding.spec)
                )
                has_free_divisible = any(
                    ax is None and d % data_size == 0 and d >= data_size
                    for d, ax in zip(leaf.shape, spec)
                )
                assert "data" in spec or not has_free_divisible, (
                    leaf.shape,
                    spec,
                )
        # params stay in their base layout (no data-axis sharding)
        for leaf in jax.tree.leaves(s_z1.params):
            assert "data" not in [ax for ax in leaf.sharding.spec if ax], (
                leaf.sharding.spec
            )

    def test_checkpoint_roundtrip_across_zero1(self, tmp_path):
        """A checkpoint written with ZeRO-1 shardings restores into the
        plain layout (and the moments carry identical values)."""
        from progen_tpu.checkpoint import (
            Package,
            get_checkpoint_fns,
            sharded_abstract_state,
        )
        from progen_tpu.training.step import abstract_train_state

        model = ProGen(TINY)
        optimizer = make_optimizer(learning_rate=1e-3)
        state, _, mesh, _ = self._steps(zero1=True, n=1)
        _, get_last, save = get_checkpoint_fns(str(tmp_path / "ck"))
        save(Package(next_seq_index=8, state=state,
                     model_config=TINY.to_dict(), run_id=None))

        boxed, abstract = abstract_train_state(model, optimizer, TINY.seq_len)
        from progen_tpu.parallel.partition import state_shardings

        plain_sh = state_shardings(boxed, mesh)
        restored = get_last(sharded_abstract_state(abstract, plain_sh)).state
        for a, b in zip(
            jax.tree.leaves(jax.device_get(state.opt_state)),
            jax.tree.leaves(jax.device_get(restored.opt_state)),
        ):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b))
