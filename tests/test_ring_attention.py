"""Ring halo-exchange sequence-parallel attention vs the single-device op."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from progen_tpu.ops.attention import local_attention
from progen_tpu.ops.pallas_attention import PALLAS_API_OK
from progen_tpu.parallel.partition import make_mesh
from progen_tpu.parallel.ring_attention import ring_local_attention


def _qkv(key, shape):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(key), 3)
    return (
        jax.random.normal(kq, shape),
        jax.random.normal(kk, shape),
        jax.random.normal(kv, shape),
    )


class TestRingAttention:
    @pytest.mark.parametrize("seq_shards", [2, 4, 8])
    def test_matches_local_attention(self, seq_shards):
        mesh = make_mesh(data=1, seq=seq_shards, model=1)
        q, k, v = _qkv(0, (2, 2, 64, 16))
        ref = local_attention(q, k, v, window_size=8)
        out = ring_local_attention(
            q, k, v, window_size=8, mesh=mesh, batch_axis=None
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)

    def test_with_data_axis_too(self):
        mesh = make_mesh(data=2, seq=4, model=1)
        q, k, v = _qkv(1, (4, 2, 32, 8))
        ref = local_attention(q, k, v, window_size=8)
        out = ring_local_attention(q, k, v, window_size=8, mesh=mesh)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)

    def test_window_zero_dilution_preserved(self):
        """Shard 0 must zero its received halo (it wraps around the ring
        from the LAST shard) — keeping the reference's window-0 softmax
        dilution instead of attending to the sequence end."""
        mesh = make_mesh(data=1, seq=4, model=1)
        q, k, v = _qkv(2, (1, 1, 32, 8))
        ref = local_attention(q, k, v, window_size=8)
        out = ring_local_attention(
            q, k, v, window_size=8, mesh=mesh, batch_axis=None
        )
        np.testing.assert_allclose(
            np.asarray(out)[:, :, :8], np.asarray(ref)[:, :, :8], atol=1e-5
        )

    def test_gradients_flow_across_shards(self):
        """d(loss)/dk at a shard boundary must include the halo
        contribution from the neighboring shard's first window."""
        mesh = make_mesh(data=1, seq=4, model=1)
        q, k, v = _qkv(3, (1, 1, 32, 8))

        def ring_loss(k):
            return ring_local_attention(
                q, k, v, window_size=8, mesh=mesh, batch_axis=None
            ).sum()

        def ref_loss(k):
            return local_attention(q, k, v, window_size=8).sum()

        g_ring = jax.grad(ring_loss)(k)
        g_ref = jax.grad(ref_loss)(k)
        np.testing.assert_allclose(
            np.asarray(g_ring), np.asarray(g_ref), atol=1e-5
        )

    def test_misaligned_shards_raise(self):
        mesh = make_mesh(data=1, seq=8, model=1)
        q, k, v = _qkv(4, (1, 1, 32, 8))  # 32/(8 shards) = 4 < window 8
        with pytest.raises(ValueError):
            ring_local_attention(
                q, k, v, window_size=8, mesh=mesh, batch_axis=None
            )


class TestRingWithPallas:
    """use_pallas=True: each shard runs the halo-aware measured kernel
    (pallas_local_attention_halo) instead of the XLA dense path — the
    long-context multi-chip composition of the two flagship features."""

    pytestmark = pytest.mark.skipif(
        not PALLAS_API_OK,
        reason="installed jax predates the Pallas kernel API family; "
        "use_pallas falls back to the XLA halo path, so the kernel "
        "this class targets never runs",
    )

    def _policy(self, monkeypatch, tmp_path, fwd="pallas", bwd="kv"):
        import json

        import progen_tpu.ops.pallas_attention as pa

        # pin a policy whose winners exercise the Pallas path at the tiny
        # per-shard shapes the 8-device CPU mesh produces
        table = tmp_path / "policy.json"
        table.write_text(json.dumps({"entries": [
            {"window": 8, "n": 16, "bh": 4,
             "fwd": fwd, "bwd": bwd, "bh_block": 1},
        ]}))
        monkeypatch.setattr(pa, "_POLICY_PATH", table)

    @pytest.mark.parametrize("seq_shards", [2, 4])
    def test_forward_matches_gathered(self, seq_shards, monkeypatch,
                                      tmp_path):
        self._policy(monkeypatch, tmp_path)
        mesh = make_mesh(data=1, seq=seq_shards, model=1)
        q, k, v = _qkv(10, (2, 2, 64, 16))
        ref = local_attention(q, k, v, window_size=8)
        out = ring_local_attention(
            q, k, v, window_size=8, mesh=mesh, batch_axis=None,
            use_pallas=True,
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-4)

    def test_gradients_cross_shards(self, monkeypatch, tmp_path):
        """The halo grad (d_halo ppermuted back to the left neighbor by
        shard_map's transpose) must reproduce the gathered-op boundary
        gradients exactly."""
        self._policy(monkeypatch, tmp_path)
        mesh = make_mesh(data=1, seq=4, model=1)
        q, k, v = _qkv(11, (1, 1, 32, 8))

        g_ring = jax.grad(lambda k_: ring_local_attention(
            q, k_, v, window_size=8, mesh=mesh, batch_axis=None,
            use_pallas=True).sum())(k)
        g_ref = jax.grad(lambda k_: local_attention(
            q, k_, v, window_size=8).sum())(k)
        np.testing.assert_allclose(
            np.asarray(g_ring), np.asarray(g_ref), atol=1e-4
        )

    def test_xla_xla_policy_skips_kernel(self, monkeypatch, tmp_path):
        """A shape whose measured winners are xla/xla must use the plain
        dense path (no custom-VJP recompute) — and still be exact."""
        self._policy(monkeypatch, tmp_path, fwd="xla", bwd="xla")
        mesh = make_mesh(data=1, seq=2, model=1)
        q, k, v = _qkv(12, (1, 1, 32, 8))
        ref = local_attention(q, k, v, window_size=8)
        out = ring_local_attention(
            q, k, v, window_size=8, mesh=mesh, batch_axis=None,
            use_pallas=True,
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)


class TestModelIntegration:
    """`config.use_ring_attn` + `ProGen(config, mesh=...)`: the explicit
    ring-collective attention as a path the real model (and therefore the
    train step) can invoke — full-model fwd/bwd parity vs the plain path."""

    def _setup(self, seq_shards, scan_layers=False, remat=False):
        import dataclasses

        from flax import linen as nn

        from progen_tpu.config import ProGenConfig
        from progen_tpu.models.progen import ProGen

        cfg = ProGenConfig(
            num_tokens=32, dim=32, seq_len=64, depth=3, window_size=8,
            global_mlp_depth=1, heads=2, dim_head=16, ff_mult=2,
            dtype="float32", scan_layers=scan_layers, remat=remat,
        )
        mesh = make_mesh(data=2, seq=seq_shards, model=1)
        plain = ProGen(cfg)
        ring = ProGen(
            dataclasses.replace(cfg, use_ring_attn=True), mesh=mesh
        )
        tokens = jax.random.randint(
            jax.random.PRNGKey(7), (4, cfg.seq_len), 1, cfg.num_tokens
        )
        params = nn.meta.unbox(
            plain.init(jax.random.PRNGKey(0), tokens)["params"]
        )
        return plain, ring, params, tokens

    @pytest.mark.parametrize("seq_shards", [2, 4])
    def test_forward_parity(self, seq_shards):
        plain, ring, params, tokens = self._setup(seq_shards)
        ref = plain.apply({"params": params}, tokens)
        out = ring.apply({"params": params}, tokens)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )

    def test_same_param_tree(self):
        # init with ring enabled must yield the identical tree (the op is
        # parameter-free; init falls back to the local path) — checkpoints
        # are interchangeable across topologies
        from flax import linen as nn

        plain, ring, params, tokens = self._setup(2)
        ring_params = nn.meta.unbox(
            ring.init(jax.random.PRNGKey(0), tokens)["params"]
        )
        assert jax.tree.structure(params) == jax.tree.structure(ring_params)

    # remat=True: long8k ships remat; jax.checkpoint over the shard_map
    # ring must give the same grads as the plain path
    @pytest.mark.parametrize("remat", [False, True])
    def test_gradient_parity(self, remat):
        plain, ring, params, tokens = self._setup(2, remat=remat)

        def loss(model, p):
            return model.apply({"params": p}, tokens).astype(jnp.float32).sum()

        g_ref = jax.jit(jax.grad(lambda p: loss(plain, p)))(params)
        g_ring = jax.jit(jax.grad(lambda p: loss(ring, p)))(params)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=3e-3, rtol=2e-5
            ),
            g_ref,
            g_ring,
        )

    def test_scan_layers_forward_parity(self):
        plain, ring, params, tokens = self._setup(2, scan_layers=True)
        ref = plain.apply({"params": params}, tokens)
        out = ring.apply({"params": params}, tokens)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )

    def test_jitted_train_step_with_ring(self):
        """The production donated train step compiles and runs with the
        ring-attention model over a (data=2, seq=2) mesh."""
        from progen_tpu.parallel.partition import put_batch
        from progen_tpu.training.optimizer import make_optimizer
        from progen_tpu.training.step import (
            compile_train_step,
            init_train_state,
        )

        _, ring, _, _ = self._setup(2)
        optimizer = make_optimizer(1e-3)
        mesh = ring.mesh
        state, shardings = init_train_state(
            ring, optimizer, jax.random.PRNGKey(0),
            ring.config.seq_len, mesh=mesh,
        )
        step = compile_train_step(ring, optimizer, state, shardings, mesh)
        batch = np.random.default_rng(0).integers(
            1, 32, size=(2, 4, ring.config.seq_len + 1)
        ).astype(np.int32)
        with mesh:
            state, metrics = step(
                state, put_batch(batch, mesh, accum_axis=True)
            )
        assert np.isfinite(float(metrics["loss"]))
