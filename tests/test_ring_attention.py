"""Ring halo-exchange sequence-parallel attention vs the single-device op."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from progen_tpu.ops.attention import local_attention
from progen_tpu.parallel.partition import make_mesh
from progen_tpu.parallel.ring_attention import ring_local_attention


def _qkv(key, shape):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(key), 3)
    return (
        jax.random.normal(kq, shape),
        jax.random.normal(kk, shape),
        jax.random.normal(kv, shape),
    )


class TestRingAttention:
    @pytest.mark.parametrize("seq_shards", [2, 4, 8])
    def test_matches_local_attention(self, seq_shards):
        mesh = make_mesh(data=1, seq=seq_shards, model=1)
        q, k, v = _qkv(0, (2, 2, 64, 16))
        ref = local_attention(q, k, v, window_size=8)
        out = ring_local_attention(
            q, k, v, window_size=8, mesh=mesh, batch_axis=None
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)

    def test_with_data_axis_too(self):
        mesh = make_mesh(data=2, seq=4, model=1)
        q, k, v = _qkv(1, (4, 2, 32, 8))
        ref = local_attention(q, k, v, window_size=8)
        out = ring_local_attention(q, k, v, window_size=8, mesh=mesh)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)

    def test_window_zero_dilution_preserved(self):
        """Shard 0 must zero its received halo (it wraps around the ring
        from the LAST shard) — keeping the reference's window-0 softmax
        dilution instead of attending to the sequence end."""
        mesh = make_mesh(data=1, seq=4, model=1)
        q, k, v = _qkv(2, (1, 1, 32, 8))
        ref = local_attention(q, k, v, window_size=8)
        out = ring_local_attention(
            q, k, v, window_size=8, mesh=mesh, batch_axis=None
        )
        np.testing.assert_allclose(
            np.asarray(out)[:, :, :8], np.asarray(ref)[:, :, :8], atol=1e-5
        )

    def test_gradients_flow_across_shards(self):
        """d(loss)/dk at a shard boundary must include the halo
        contribution from the neighboring shard's first window."""
        mesh = make_mesh(data=1, seq=4, model=1)
        q, k, v = _qkv(3, (1, 1, 32, 8))

        def ring_loss(k):
            return ring_local_attention(
                q, k, v, window_size=8, mesh=mesh, batch_axis=None
            ).sum()

        def ref_loss(k):
            return local_attention(q, k, v, window_size=8).sum()

        g_ring = jax.grad(ring_loss)(k)
        g_ref = jax.grad(ref_loss)(k)
        np.testing.assert_allclose(
            np.asarray(g_ring), np.asarray(g_ref), atol=1e-5
        )

    def test_misaligned_shards_raise(self):
        mesh = make_mesh(data=1, seq=8, model=1)
        q, k, v = _qkv(4, (1, 1, 32, 8))  # 32/(8 shards) = 4 < window 8
        with pytest.raises(ValueError):
            ring_local_attention(
                q, k, v, window_size=8, mesh=mesh, batch_axis=None
            )
