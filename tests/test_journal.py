"""Request replay journal: crash-safe recovery of accepted work.

The contract under test (serving/journal.py): every request the
scheduler ACCEPTED either completes in the original process or is
reconstructed bit-identically by replay — and work a client already saw
(journaled tokens, settled requests) is never re-emitted. The parity
half rides on the same ``sample_fast`` pin as test_serving.py: a
resumed stream must equal the uninterrupted one token-for-token.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from progen_tpu.config import ProGenConfig
from progen_tpu.models.progen import ProGen
from progen_tpu.sampling import sample_fast
from progen_tpu.serving import (
    Request,
    RequestJournal,
    Scheduler,
    ServeEngine,
    replay_into,
    replay_requests,
)
from progen_tpu.serving.journal import _advance_key
from progen_tpu.telemetry.trace import LineDrops

TINY = ProGenConfig(
    num_tokens=32,
    dim=32,
    seq_len=32,
    depth=2,
    window_size=8,
    global_mlp_depth=1,
    heads=2,
    dim_head=16,
    ff_mult=2,
    dtype="float32",
)


@pytest.fixture(scope="module")
def model_and_params():
    model = ProGen(TINY)
    tokens = jnp.zeros((1, TINY.seq_len), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), tokens)
    from flax.core import meta

    return model, meta.unbox(variables)["params"]


def _fresh(model, params, journal_path):
    engine = ServeEngine(model, params, max_slots=2, max_len=24)
    sched = Scheduler(engine, journal=RequestJournal(journal_path))
    return engine, sched


def _reference(model, params, req):
    key = req.key if req.key is not None else jax.random.PRNGKey(req.seed)
    return np.asarray(
        sample_fast(
            key, model, params, jnp.asarray(req.prime, jnp.int32),
            req.length, top_k=req.top_k, add_bos=req.add_bos,
            temperature=req.temperature, top_p=req.top_p,
        )
    )


class TestJournalRecords:
    def test_accept_round_trip(self, tmp_path):
        """An accept record carries everything needed to re-create the
        request from nothing — including the key resolved from a seed."""
        path = tmp_path / "journal.jsonl"
        j = RequestJournal(path)
        req = Request(
            id="a", prime=np.asarray([3, 5, 9], np.int32), length=12,
            top_k=7, add_bos=True, temperature=0.8, top_p=0.9, seed=5,
        )
        j.accept(req)
        j.close()

        pending, finished, n_done = replay_requests(path)
        assert finished == [] and n_done == 0
        (r,) = pending
        assert r.id == "a"
        np.testing.assert_array_equal(r.prime, req.prime)
        assert (r.length, r.top_k, r.add_bos) == (12, 7, True)
        assert (r.temperature, r.top_p) == (0.8, 0.9)
        np.testing.assert_array_equal(
            np.asarray(r.key), np.asarray(jax.random.PRNGKey(5))
        )
        # queue-TTL deadlines measured wait in the dead process; replay
        # must not re-apply them
        assert r.deadline_s is None

    def test_token_watermarks_fold_into_resume_state(self, tmp_path):
        """Journaled tokens extend the prime and fast-forward the key by
        exactly one split per emitted token."""
        path = tmp_path / "journal.jsonl"
        j = RequestJournal(path)
        key0 = jax.random.PRNGKey(11)
        req = Request(
            id="a", prime=np.asarray([3, 5], np.int32), length=10,
            add_bos=False, key=key0,
        )
        j.accept(req)
        for i, t in enumerate([11, 12, 13]):
            j.token("a", 2 + i, t)  # first generated index == len(prime)
        j.close()

        (r,), finished, _ = replay_requests(path)
        assert finished == []
        np.testing.assert_array_equal(
            r.prime, np.asarray([3, 5, 11, 12, 13], np.int32)
        )
        want = jax.random.PRNGKey(11)
        want = jax.random.split(want)[0]
        want = jax.random.split(want)[0]
        want = jax.random.split(want)[0]
        want = np.asarray(want)
        np.testing.assert_array_equal(np.asarray(r.key), want)
        np.testing.assert_array_equal(
            np.asarray(_advance_key(jax.random.PRNGKey(11), 3)), want
        )

    def test_torn_tail_and_garbage_skipped(self, tmp_path):
        """A SIGKILL tears at most the final line; replay must survive it
        (and stray garbage) while counting what it skipped."""
        path = tmp_path / "journal.jsonl"
        j = RequestJournal(path)
        j.accept(Request(id="a", prime=np.asarray([3], np.int32), length=8))
        j.token("a", 1, 9)
        j.close()
        with path.open("a") as f:
            f.write("not json at all\n")
            f.write('{"ev": "journal", "op": "token", "req": "a", "ind')

        drops = LineDrops()
        (r,), _, _ = replay_requests(path, drops)
        assert drops.count == 2
        np.testing.assert_array_equal(r.prime, np.asarray([3, 9], np.int32))

    def test_done_skips_replay(self, tmp_path):
        path = tmp_path / "journal.jsonl"
        j = RequestJournal(path)
        j.accept(Request(id="a", prime=np.asarray([3], np.int32), length=8))
        j.done("a", "completed", 6)
        j.close()
        pending, finished, n_done = replay_requests(path)
        assert pending == [] and finished == [] and n_done == 1

    def test_stream_that_hit_its_stop_rule_is_finished(self, tmp_path):
        """Died after the last token but before the done record: nothing
        to decode — replay settles it instead of resubmitting."""
        path = tmp_path / "journal.jsonl"
        j = RequestJournal(path)
        j.accept(Request(
            id="full", prime=np.asarray([3, 5], np.int32), length=6,
            add_bos=False,
        ))
        for i, t in enumerate([7, 8, 9, 1]):
            j.token("full", 2 + i, t)  # start + 4 == length
        # second-zero stop: BOS pads a zero, the emitted 0 is the second
        j.accept(Request(
            id="eos", prime=np.asarray([3], np.int32), length=20,
            add_bos=True,
        ))
        j.token("eos", 2, 5)
        j.token("eos", 3, 0)
        j.close()

        pending, finished, n_done = replay_requests(path)
        assert pending == [] and n_done == 0
        by_id = {f["id"]: f for f in finished}
        assert by_id["full"]["emitted"] == [7, 8, 9, 1]
        assert by_id["eos"]["emitted"] == [5, 0]

    def test_emit_after_close_is_a_noop(self, tmp_path):
        path = tmp_path / "journal.jsonl"
        j = RequestJournal(path)
        j.done("a", "completed")
        j.close()
        j.token("a", 2, 5)  # late writer during teardown: dropped
        assert path.read_text().count("\n") == 1


class TestCrashResume:
    def test_kill_mid_decode_resumes_bit_identically(
        self, tmp_path, model_and_params
    ):
        """The tentpole invariant, in-process: run a journaled scheduler
        for a few steps, abandon it (the in-process stand-in for
        SIGKILL), replay into a FRESH engine+scheduler, and require
        (a) zero lost accepted requests, (b) zero duplicate
        (request, index) emissions, (c) every emitted token — before and
        after the crash — equal to the uninterrupted ``sample_fast``
        stream, (d) completions bit-equal to the reference buffer."""
        model, params = model_and_params
        path = tmp_path / "journal.jsonl"
        rng = np.random.RandomState(3)
        knob_grid = [
            {},
            {"temperature": 0.7, "add_bos": True},
            {"top_p": 0.9},
            {"top_k": 5, "temperature": 1.2},
        ]
        reqs = []
        for i in range(4):
            prime = rng.randint(1, TINY.num_tokens, size=rng.randint(1, 5))
            reqs.append(Request(
                id=f"r{i}", prime=prime.astype(np.int32),
                length=int(rng.randint(len(prime) + 3, 22)),
                key=jax.random.PRNGKey(500 + i), **knob_grid[i],
            ))

        _, sched1 = _fresh(model, params, path)
        for req in reqs:
            ok, reason = sched1.submit(req)
            assert ok, reason
        ev1, comp1 = [], []
        for _ in range(5):
            ev, comp = sched1.step()
            ev1.extend(ev)
            comp1.extend(comp)
        assert ev1, "no tokens journaled before the crash"
        sched1.journal.close()  # the process is now 'dead'

        eng2, sched2 = _fresh(model, params, path)
        summary = replay_into(sched2, path)
        ev2, comp2 = sched2.run_to_completion(max_steps=500)

        done1 = {c.request_id for c in comp1}
        resumed = {r.id for r in summary["resumed"]}
        settled = {f["id"] for f in summary["finished"]}
        # (a) every accepted request is accounted for exactly once
        assert summary["rejected"] == []
        assert done1 | resumed | settled == {r.id for r in reqs}
        assert summary["skipped_done"] == len(done1)
        by_id2 = {c.request_id: c for c in comp2}
        assert set(by_id2) == resumed
        # (b) no (request, index) emitted twice across the two lives
        pairs = [(e.request_id, e.index) for e in ev1 + ev2]
        assert len(set(pairs)) == len(pairs)
        # (c) + (d) bit-parity with the uninterrupted stream
        for req in reqs:
            ref = _reference(model, params, req)
            for e in ev1 + ev2:
                if e.request_id == req.id:
                    assert ref[e.index] == e.token, (req.id, e.index)
            if req.id in by_id2:
                np.testing.assert_array_equal(by_id2[req.id].tokens, ref)
        assert (
            sched2.metrics.counters["journal_replayed"]
            == len(summary["resumed"])
        )

        # dedup composes: a third replay of the (now fully settled)
        # journal resumes nothing and skips everything
        sched3 = Scheduler(eng2, journal=RequestJournal(path))
        again = replay_into(sched3, path)
        assert again["resumed"] == [] and again["finished"] == []
        assert again["skipped_done"] == len(reqs)

    def test_shed_requests_are_settled_not_replayed(
        self, tmp_path, model_and_params
    ):
        """Drained/expired requests were answered ('rejected: ...') —
        replay must not resurrect them."""
        model, params = model_and_params
        path = tmp_path / "journal.jsonl"
        _, sched = _fresh(model, params, path)
        for i in range(3):
            ok, _ = sched.submit(Request(
                id=f"q{i}", prime=np.asarray([4 + i], np.int32), length=8,
            ))
            assert ok
        assert sched.drain_queue() == 3
        sched.journal.close()

        pending, finished, n_done = replay_requests(path)
        assert pending == [] and finished == [] and n_done == 3

    def test_close_tracks_does_not_settle(
        self, tmp_path, model_and_params
    ):
        """The second-signal 'exit now' path closes trace tracks but
        journals nothing: killed requests were never answered, so they
        MUST come back on replay."""
        model, params = model_and_params
        path = tmp_path / "journal.jsonl"
        _, sched = _fresh(model, params, path)
        for i in range(2):
            ok, _ = sched.submit(Request(
                id=f"k{i}", prime=np.asarray([4 + i], np.int32),
                length=20, key=jax.random.PRNGKey(i),
            ))
            assert ok
        sched.step()  # both admitted, one token each
        sched.close_tracks("killed")
        sched.journal.close()

        pending, _, n_done = replay_requests(path)
        assert n_done == 0
        assert {r.id for r in pending} == {"k0", "k1"}
        for r in pending:
            assert len(r.prime) == 2  # original 1-token prime + 1 emitted

    def test_replay_settles_finished_and_second_replay_skips(
        self, tmp_path, model_and_params
    ):
        model, params = model_and_params
        path = tmp_path / "journal.jsonl"
        j = RequestJournal(path)
        j.accept(Request(
            id="full", prime=np.asarray([3, 5], np.int32), length=6,
            add_bos=False,
        ))
        for i, t in enumerate([7, 8, 9, 1]):
            j.token("full", 2 + i, t)
        j.close()

        _, sched = _fresh(model, params, path)
        summary = replay_into(sched, path)
        assert [f["id"] for f in summary["finished"]] == ["full"]
        assert summary["resumed"] == []
        assert not sched.has_work  # settled, not resubmitted

        again = replay_into(sched, path)  # the done record was journaled
        assert again["finished"] == [] and again["skipped_done"] == 1
