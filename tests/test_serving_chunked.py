"""Chunked prefill + prefix cache: bit-parity and accounting.

The chunked admission path exists to kill the admission stall, not to
change a single token: a request admitted chunk-at-a-time (any chunk
size, any interleaving with live decodes, hot or cold prefix cache)
must produce EXACTLY the stream the monolithic ``engine.prefill`` path
produces — which tests/test_serving.py already pins to ``sample_fast``.
Every parity test here asserts token-for-token equality between the two
admission paths on the same requests.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from progen_tpu.config import ProGenConfig
from progen_tpu.models.progen import ProGen
from progen_tpu.serving import (
    PrefixCache,
    Request,
    Scheduler,
    ServeEngine,
)
from progen_tpu.serving.engine import PreparedParams

TINY = ProGenConfig(
    num_tokens=32,
    dim=32,
    seq_len=32,
    depth=2,
    window_size=8,
    global_mlp_depth=1,
    heads=2,
    dim_head=16,
    ff_mult=2,
    dtype="float32",
)


@pytest.fixture(scope="module")
def model_and_params():
    model = ProGen(TINY)
    tokens = jnp.zeros((1, TINY.seq_len), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), tokens)
    from flax.core import meta

    return model, meta.unbox(variables)["params"]


def _requests(n, with_infill=False):
    """Overlapping requests with mixed primes/lengths/knobs, long
    enough primes that chunking actually splits them."""
    rng = np.random.RandomState(13)
    knob_grid = [
        {},
        {"temperature": 0.7},
        {"top_p": 0.9},
        {"top_k": None},
        {"add_bos": True},
        {"temperature": 1.2, "top_k": 5},
    ]
    reqs = []
    for i in range(n):
        plen = int(rng.randint(6, 16))
        prime = rng.randint(1, TINY.num_tokens, size=plen)
        knobs = dict(knob_grid[i % len(knob_grid)])
        length = int(
            rng.randint(plen + 2 + knobs.get("add_bos", False), 31)
        )
        kwargs = {}
        if with_infill and i % 2 == 0:
            template = np.zeros((length,), np.int32)
            frozen = np.zeros((length,), bool)
            for p in range(plen + 1, length - 1, 3):
                frozen[p] = True
                template[p] = int(rng.randint(1, TINY.num_tokens))
            kwargs = {"template": template, "frozen": frozen}
        reqs.append(
            Request(
                id=f"r{i}", prime=prime, length=length,
                key=jax.random.PRNGKey(4000 + i), **knobs, **kwargs,
            )
        )
    return reqs


def _run(model, params, reqs, **sched_kwargs):
    """Serve ``reqs`` through a fresh engine+scheduler; returns
    ({id: completion_tokens}, {id: [streamed tokens]}, sched)."""
    engine = ServeEngine(model, params, max_slots=3, max_len=32)
    sched = Scheduler(engine, max_queue=len(reqs) + 1, **sched_kwargs)
    for req in reqs:
        ok, reason = sched.submit(req)
        assert ok, reason
    events, completions = sched.run_to_completion(max_steps=5000)
    assert len(completions) == len(reqs)
    streams = {r.id: [] for r in reqs}
    for e in events:
        streams[e.request_id].append((e.index, e.token))
    return (
        {c.request_id: c.tokens for c in completions},
        streams,
        sched,
    )


class TestChunkedParity:
    @pytest.mark.parametrize("chunk", [1, 3, 64])
    def test_chunked_matches_monolithic(self, model_and_params, chunk):
        """Same requests through the monolithic inline path and the
        chunked path (chunk sizes below, around, and ABOVE every prime
        length) — completions and streamed (index, token) pairs must be
        bit-identical."""
        model, params = model_and_params
        reqs = _requests(6)
        mono, mono_streams, _ = _run(model, params, reqs)
        chunked, chunked_streams, _ = _run(
            model, params, reqs, prefill_chunk=chunk
        )
        for req in reqs:
            np.testing.assert_array_equal(
                chunked[req.id], mono[req.id],
                err_msg=f"{req.id} diverged at prefill_chunk={chunk}",
            )
            assert chunked_streams[req.id] == mono_streams[req.id]

    def test_chunked_infill_matches_monolithic(self, model_and_params):
        """Templates/frozen masks ride the pending state and scatter
        only on the final chunk — the infill constraint must survive
        chunking bit-for-bit."""
        model, params = model_and_params
        reqs = _requests(6, with_infill=True)
        mono, _, _ = _run(model, params, reqs)
        chunked, _, _ = _run(model, params, reqs, prefill_chunk=2)
        for req in reqs:
            np.testing.assert_array_equal(chunked[req.id], mono[req.id])
            if req.frozen is not None:
                frozen = np.asarray(req.frozen, bool)
                tpl = np.asarray(req.template, np.int32)
                got = np.asarray(chunked[req.id])
                # frozen positions actually hold the template tokens
                # (cheap sanity that the constraint was applied at all)
                reached = np.arange(len(got)) < len(got)
                m = frozen & reached & (got != 0)
                assert np.all(got[m] == tpl[m])

    def test_engine_level_resume_split_points(self, model_and_params):
        """Drive begin/advance directly with ragged budgets (1, then 2,
        then the rest) and compare against a monolithic prefill of the
        same request on a twin engine: the pool state that matters —
        the produced stream — must match."""
        model, params = model_and_params
        prime = np.asarray([3, 9, 4, 17, 2, 11, 5, 8, 21, 6], np.int32)
        kwargs = dict(top_k=25, key=jax.random.PRNGKey(7))

        def drain(engine, slot, start):
            out = []
            for _ in range(40):
                sampled, was_live, finished = engine.decode_step()
                if not was_live[slot]:
                    break
                out.append(int(sampled[slot]))
                if finished[slot]:
                    break
            return out

        e1 = ServeEngine(model, params, max_slots=2, max_len=32)
        s1 = e1.acquire()
        start1 = e1.prefill(s1, prime, 24, **kwargs)
        t1 = drain(e1, s1, start1)

        e2 = ServeEngine(model, params, max_slots=2, max_len=32)
        s2 = e2.acquire()
        pending = e2.begin_prefill(s2, prime, 24, **kwargs)
        assert not pending.done
        assert e2.advance_prefill(pending, 1) is False
        assert pending.pos == 1
        assert e2.advance_prefill(pending, 2) is False
        assert pending.pos == 3
        assert e2.advance_prefill(pending, None) is True
        assert pending.start == start1
        t2 = drain(e2, s2, pending.start)
        assert t1 == t2


class TestPrefixCache:
    def test_hit_stream_bit_identical(self, model_and_params):
        """The same scaffold served cold then cache-hot: the hot
        request must stream the exact cold tokens, and the cache must
        actually have been used (hits > 0, fewer prefill positions fed
        through the model)."""
        model, params = model_and_params
        prime = np.asarray(
            [5, 12, 3, 3, 8, 19, 2, 7, 14, 9, 4, 22], np.int32
        )
        reqs = [
            Request(id="cold", prime=prime, length=28,
                    key=jax.random.PRNGKey(11)),
            Request(id="hot", prime=prime, length=28,
                    key=jax.random.PRNGKey(11)),
        ]
        mono, _, _ = _run(model, params, reqs[:1])
        cache = PrefixCache(64 << 20)
        engine = ServeEngine(model, params, max_slots=2, max_len=32)
        sched = Scheduler(engine, max_queue=4, prefill_chunk=4,
                          prefix_cache=cache)
        ok, _ = sched.submit(reqs[0])
        assert ok
        _, comps0 = sched.run_to_completion(max_steps=2000)
        ok, _ = sched.submit(reqs[1])
        assert ok
        _, comps1 = sched.run_to_completion(max_steps=2000)

        np.testing.assert_array_equal(comps0[0].tokens, mono["cold"])
        np.testing.assert_array_equal(comps1[0].tokens, mono["cold"])
        assert cache.hits >= 1
        m = sched.metrics.snapshot()
        assert m["prefix_cache_hits"] >= 1
        # the hot request skipped its whole feed region
        assert m["prefix_cache_hit_tokens"] >= len(prime) - 1

    def test_hit_with_different_sampling_knobs(self, model_and_params):
        """Cache keys are sampling-irrelevant: a hit may seed a request
        with different temperature/key, and the result must equal that
        request's own monolithic decode (NOT the cached request's)."""
        model, params = model_and_params
        prime = np.asarray([4, 9, 17, 2, 6, 13, 21, 3, 8, 5], np.int32)
        r_a = Request(id="a", prime=prime, length=26,
                      key=jax.random.PRNGKey(1))
        r_b = Request(id="b", prime=prime, length=26, temperature=0.7,
                      top_k=5, key=jax.random.PRNGKey(2))
        mono, _, _ = _run(model, params, [r_a, r_b])
        cache = PrefixCache(64 << 20)
        engine = ServeEngine(model, params, max_slots=2, max_len=32)
        sched = Scheduler(engine, max_queue=4, prefill_chunk=3,
                          prefix_cache=cache)
        for r in (r_a, r_b):
            ok, _ = sched.submit(r)
            assert ok
        _, comps = sched.run_to_completion(max_steps=2000)
        by_id = {c.request_id: c.tokens for c in comps}
        np.testing.assert_array_equal(by_id["a"], mono["a"])
        np.testing.assert_array_equal(by_id["b"], mono["b"])
        assert cache.hits >= 1

    def test_lru_byte_budget_eviction(self):
        """Unit-level LRU: inserting past the byte budget evicts the
        least-recently-used snapshot first; bytes never exceed the
        budget; a refreshed entry survives over a stale one."""
        snap = {"k": np.zeros((1024,), np.float32)}  # 4096 bytes
        cache = PrefixCache(3 * 4096)
        rows = [np.full((8,), i + 1, np.int32) for i in range(4)]
        for i in range(3):
            assert cache.insert(rows[i], 8, snap)
        assert len(cache) == 3 and cache.bytes == 3 * 4096
        # refresh row0 so row1 becomes LRU
        depth, got = cache.lookup(rows[0], 8)
        assert depth == 8 and got is snap
        cache.insert(rows[3], 8, snap)
        assert len(cache) == 3
        assert cache.bytes <= cache.max_bytes
        assert cache.evictions == 1
        assert cache.lookup(rows[1], 8)[1] is None  # LRU was evicted
        assert cache.lookup(rows[0], 8)[1] is not None
        assert cache.lookup(rows[3], 8)[1] is not None

    def test_lookup_depth_capped_and_deepest_wins(self):
        snap = {"k": np.zeros((16,), np.float32)}
        cache = PrefixCache(1 << 20)
        row = np.arange(1, 17, dtype=np.int32)
        cache.insert(row, 4, snap)
        cache.insert(row, 8, snap)
        depth, got = cache.lookup(row, 16)
        assert depth == 8 and got is not None
        # feed region shorter than the deepest snapshot: cap applies
        depth, got = cache.lookup(row, 6)
        assert depth == 4
        # diverging prefix: no hit at all
        other = row.copy()
        other[2] = 30
        assert cache.lookup(other, 16) == (0, None)

    def test_oversized_snapshot_is_skipped(self):
        cache = PrefixCache(100)
        big = {"k": np.zeros((1024,), np.float32)}
        assert not cache.insert(np.arange(4, dtype=np.int32), 4, big)
        assert len(cache) == 0 and cache.bytes == 0

    def test_commit_params_clears_snapshots(self, model_and_params):
        """Hot reload invalidation: snapshots were computed under the
        old weights; commit_params must drop them (counters survive)."""
        model, params = model_and_params
        engine = ServeEngine(model, params, max_slots=2, max_len=32)
        cache = PrefixCache(64 << 20)
        engine.set_prefix_cache(cache)
        slot = engine.acquire()
        pending = engine.begin_prefill(
            slot, np.asarray([3, 7, 2, 9, 4], np.int32), 16,
            key=jax.random.PRNGKey(0),
        )
        engine.advance_prefill(pending, 2)
        assert len(cache) >= 1
        inserts = cache.inserts
        engine.commit_params(
            PreparedParams(engine.params, None, None, None)
        )
        assert len(cache) == 0 and cache.bytes == 0
        assert cache.inserts == inserts  # counters not reset


class TestCompileFlatness:
    def test_compile_counts_flat_under_interleaved_traffic(
        self, model_and_params
    ):
        """After one warmup admission, mixed chunked traffic — varied
        primes, chunk boundaries, prefix-cache hits and misses — must
        not compile a single new program: the chunk program's bounds
        are traced, the finish program is shape-fixed, decode is
        untouched."""
        model, params = model_and_params
        engine = ServeEngine(model, params, max_slots=3, max_len=32)
        cache = PrefixCache(64 << 20)
        sched = Scheduler(engine, max_queue=16, prefill_chunk=2,
                          prefix_cache=cache)
        warm = Request(id="warm", prime=np.asarray([3, 5, 7], np.int32),
                       length=12, key=jax.random.PRNGKey(0))
        ok, _ = sched.submit(warm)
        assert ok
        sched.run_to_completion(max_steps=2000)
        decode_after = ServeEngine.decode_compile_count()
        prefill_after = ServeEngine.prefill_compile_count()

        for req in _requests(6):
            ok, reason = sched.submit(req)
            assert ok, reason
        sched.run_to_completion(max_steps=5000)
        assert ServeEngine.decode_compile_count() == decode_after
        assert ServeEngine.prefill_compile_count() == prefill_after
        m = sched.metrics.snapshot()
        assert m["decode_compile_count"] == decode_after
        assert m["prefill_compile_count"] == prefill_after


class TestOccupancyMidChunk:
    def test_slot_counts_occupied_during_chunked_prefill(
        self, model_and_params
    ):
        """The gauge fix: a slot mid-chunked-prefill is OCCUPIED. With
        chunk=1 and a long prime, the pending admission spans many
        steps — slot_occupancy must show 1 (and slots_free max-1) the
        whole way, not flap free between chunks."""
        model, params = model_and_params
        engine = ServeEngine(model, params, max_slots=2, max_len=32)
        sched = Scheduler(engine, max_queue=4, prefill_chunk=1)
        prime = np.arange(1, 13, dtype=np.int32)
        req = Request(id="long", prime=prime, length=30,
                      key=jax.random.PRNGKey(3))
        ok, _ = sched.submit(req)
        assert ok
        saw_pending = 0
        while sched.has_work:
            sched.step()
            if sched._pending is not None:
                saw_pending += 1
                m = sched.metrics.snapshot()
                assert m["slot_occupancy"] == 1
                assert m["slots_free"] == 1
        # the prime is long and the chunk is 1: the pending state must
        # have been observable across multiple steps
        assert saw_pending >= 3
        m = sched.metrics.snapshot()
        assert m["slot_occupancy"] == 0
        assert m["slots_free"] == 2
