"""Fleet SLO watchtower (telemetry/slo.py): burn-rate math on synthetic
series, window-edge behavior, torn/stale exposition files, the
transition-record state machine, and the slo-report CLI's exit-code
contract (0 ok / 1 warn / 2 burning)."""

import json
import os
import time

import pytest
from click.testing import CliRunner

from progen_tpu.cli.telemetry import main as telemetry_cli
from progen_tpu.telemetry.slo import (
    STATE_BURNING,
    STATE_OK,
    STATE_RESOLVED,
    STATE_WARN,
    Objective,
    SloConfig,
    SloWatch,
    evaluate,
    exit_code,
    load_objectives,
    parse_prom_text,
    read_prom_file,
    render_report,
    samples_from_metrics,
)

OBJECTIVES_TOML = """
[windows]
short_s = 60
long_s = 600

[burn]
warn = 1.0
hot = 2.0
stale_after_s = 30

[objective_ttft_p95]
kind = "latency"
metric = "ttft_s"
quantile = "p95"
threshold_s = 1.0

[objective_error_rate]
kind = "ratio"
bad = "requests_rejected"
total = "requests_submitted"
budget = 0.1

[objective_availability]
kind = "availability"
gauge = "replicas_up"
min_value = 2.0
target = 0.9
"""


@pytest.fixture
def cfg(tmp_path):
    p = tmp_path / "slo.toml"
    p.write_text(OBJECTIVES_TOML)
    return load_objectives(p)


def rows(points):
    """(t, submitted, rejected, up, ttft_p95) tuples → metrics.jsonl
    rows in the tracker's router/ stream shape."""
    return [
        {
            "_time": t,
            "router/requests_submitted": float(sub),
            "router/requests_rejected": float(rej),
            "router/replicas_up": float(up),
            "router/ttft_s_p95_s": float(ttft),
        }
        for t, sub, rej, up, ttft in points
    ]


def series_for(points):
    return [samples_from_metrics(rows(points))]


def by_name(results):
    return {r.objective: r for r in results}


class TestTomlLoading:
    def test_shipped_default_parses(self):
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        cfg = load_objectives(
            os.path.join(repo, "configs", "serving", "slo.toml")
        )
        kinds = {o.name: o.kind for o in cfg.objectives}
        assert kinds == {
            "ttft_p95": "latency", "latency_p99": "latency",
            "error_rate": "ratio", "availability": "availability",
        }

    def test_windows_and_thresholds(self, cfg):
        assert cfg.short_s == 60 and cfg.long_s == 600
        assert cfg.warn == 1.0 and cfg.hot == 2.0
        assert cfg.stale_after_s == 30

    def test_unknown_kind_rejected(self, tmp_path):
        p = tmp_path / "bad.toml"
        p.write_text(
            "[objective_x]\nkind = \"throughput\"\n"
        )
        with pytest.raises(ValueError, match="unknown kind"):
            load_objectives(p)

    def test_bad_quantile_rejected(self, tmp_path):
        p = tmp_path / "bad.toml"
        p.write_text(
            "[objective_x]\nkind = \"latency\"\nmetric = \"ttft_s\"\n"
            "quantile = \"p42\"\n"
        )
        with pytest.raises(ValueError, match="p42"):
            load_objectives(p)

    def test_empty_rejected(self, tmp_path):
        p = tmp_path / "empty.toml"
        p.write_text("[windows]\nshort_s = 60\n")
        with pytest.raises(ValueError, match="no .objective"):
            load_objectives(p)


class TestPromParsing:
    def test_counters_gauges_quantiles_normalized(self):
        text = (
            "# TYPE progen_router_requests_submitted_total counter\n"
            "progen_router_requests_submitted_total 10\n"
            "# TYPE progen_router_replicas_up gauge\n"
            "progen_router_replicas_up 2\n"
            "# TYPE progen_serve_ttft_seconds summary\n"
            'progen_serve_ttft_seconds{quantile="0.95"} 0.5\n'
            "progen_serve_ttft_seconds_sum 1.25\n"
            "progen_serve_ttft_seconds_count 4\n"
        )
        got = parse_prom_text(text)
        assert got == {
            "requests_submitted": 10.0,
            "replicas_up": 2.0,
            "ttft_s_p95_s": 0.5,
            "ttft_s_sum": 1.25,
            "ttft_s_count": 4.0,
        }

    def test_torn_lines_skipped_never_fatal(self):
        text = (
            "progen_router_replicas_up 2\n"
            "progen_router_requests_submi"  # torn mid-write
        )
        assert parse_prom_text(text) == {"replicas_up": 2.0}
        assert parse_prom_text("!!! garbage\n\x00\n") == {}
        assert parse_prom_text("progen_router_x notanumber\n") == {}

    def test_read_prom_file_age_and_missing(self, tmp_path):
        p = tmp_path / "m.prom"
        p.write_text("progen_router_replicas_up 2\n")
        old = time.time() - 120
        os.utime(p, (old, old))
        age, vals = read_prom_file(p)
        assert 115 < age < 130
        assert vals == {"replicas_up": 2.0}
        assert read_prom_file(tmp_path / "gone.prom") is None


class TestSamplesFromMetrics:
    def test_prefix_stripped_and_sorted(self):
        out = samples_from_metrics([
            {"_time": 2.0, "serve/ttft_s_p95_s": 0.2},
            {"_time": 1.0, "router/replicas_up": 2, "_step": 3,
             "note": "strings dropped"},
            {"no_time": True},
        ])
        assert out == [
            (1.0, {"replicas_up": 2.0}),
            (2.0, {"ttft_s_p95_s": 0.2}),
        ]


class TestBurnRates:
    def test_all_healthy_exit_zero(self, cfg):
        pts = [(t, 10 * t, 0, 2, 0.3) for t in range(1, 20)]
        res = evaluate(cfg, series_for(pts))
        assert {r.state for r in res} == {STATE_OK}
        assert exit_code(res) == 0

    def test_latency_burn_is_value_over_threshold(self, cfg):
        pts = [(100.0, 10, 0, 2, 0.5)]
        r = by_name(evaluate(cfg, series_for(pts)))["ttft_p95"]
        assert r.burn_short == pytest.approx(0.5)
        assert r.state == STATE_OK
        pts = [(100.0, 10, 0, 2, 1.5)]
        r = by_name(evaluate(cfg, series_for(pts)))["ttft_p95"]
        assert r.burn_short == pytest.approx(1.5)
        assert r.state == STATE_WARN
        pts = [(100.0, 10, 0, 2, 2.5)]
        r = by_name(evaluate(cfg, series_for(pts)))["ttft_p95"]
        assert r.state == STATE_BURNING

    def test_ratio_windowed_delta(self, cfg):
        # old samples: 50% rejected — but all outside both windows'
        # deltas (counters flat since); windows judge the RECENT burn
        pts = [
            (0.0, 100, 50, 2, 0.1),
            (500.0, 100, 50, 2, 0.1),
            (1000.0, 200, 50, 2, 0.1),  # 100 new, 0 rejected
        ]
        r = by_name(evaluate(cfg, series_for(pts)))["error_rate"]
        assert r.burn_long == pytest.approx(0.0)
        assert r.state == STATE_OK

    def test_ratio_fast_burn_both_windows_pages(self, cfg):
        # half of recent requests rejected against a 10% budget → both
        # windows far over hot → burning → exit 2
        pts = [
            (940.0, 100, 0, 2, 0.1),
            (990.0, 200, 50, 2, 0.1),
            (1000.0, 300, 100, 2, 0.1),
        ]
        res = evaluate(cfg, series_for(pts))
        r = by_name(res)["error_rate"]
        # short window [940, 1000]: 100 rejected of 200 new → burn 5
        assert r.burn_short == pytest.approx(5.0)
        # long window [400, 1000]: 100 of 300 → burn 10/3
        assert r.burn_long == pytest.approx(10.0 / 3.0)
        assert r.state == STATE_BURNING
        assert exit_code(res) == 2

    def test_ratio_slow_burn_warns_not_pages(self, cfg):
        # long window over budget, short window clean → warn, not page
        pts = [
            (400.0, 100, 0, 2, 0.1),
            (500.0, 200, 25, 2, 0.1),   # the incident, long ago
            (1000.0, 300, 25, 2, 0.1),  # short window: clean
        ]
        res = evaluate(cfg, series_for(pts))
        r = by_name(res)["error_rate"]
        assert r.burn_short == pytest.approx(0.0)
        # 25 rejected of 200 new in [400, 1000] → 0.125/0.1 budget
        assert r.burn_long == pytest.approx(1.25)
        assert r.state == STATE_WARN
        assert exit_code(res) == 1

    def test_counter_reset_not_negative(self, cfg):
        # process restart mid-window: counters drop to near zero; the
        # delta must fall back to the post-restart value, never negative
        pts = [
            (900.0, 1000, 100, 2, 0.1),
            (950.0, 20, 10, 2, 0.1),   # restarted
            (1000.0, 40, 10, 2, 0.1),
        ]
        r = by_name(evaluate(cfg, series_for(pts)))["error_rate"]
        assert r.burn_short is not None and r.burn_short >= 0.0

    def test_availability_burn(self, cfg):
        # half the window samples below min replicas vs a 90% target →
        # burn 5 on both windows → burning
        pts = [(1000.0 + i, 10, 0, (2 if i % 2 else 1), 0.1)
               for i in range(20)]
        r = by_name(evaluate(cfg, series_for(pts)))["availability"]
        assert r.burn_long == pytest.approx(5.0)
        assert r.state == STATE_BURNING

    def test_window_edge_sample_exactly_at_boundary(self, cfg):
        # a sample exactly at now-short_s is IN the short window
        pts = [(940.0, 100, 0, 1, 0.1), (1000.0, 100, 0, 2, 0.1)]
        r = by_name(
            evaluate(cfg, series_for(pts), now=1000.0)
        )["availability"]
        # 1 of 2 in-window samples below min → burn (0.5)/(0.1) = 5
        assert r.burn_short == pytest.approx(5.0)

    def test_no_data_is_warn_not_ok(self, cfg):
        res = evaluate(cfg, [])
        assert {r.state for r in res} == {STATE_WARN}
        assert exit_code(res) == 1

    def test_latency_from_fresh_prom_overrides_nothing_stale(self, cfg):
        proms = [(5.0, {"ttft_s_p95_s": 2.5})]  # fresh, hot
        r = by_name(evaluate(cfg, [], proms=proms))["ttft_p95"]
        assert r.state == STATE_BURNING

    def test_stale_prom_is_warn(self, cfg):
        # the ONLY evidence is an expired textfile → liveness problem
        proms = [(120.0, {"ttft_s_p95_s": 0.1})]  # stale (>30s)
        r = by_name(evaluate(cfg, [], proms=proms))["ttft_p95"]
        assert r.state == STATE_WARN
        assert "stale" in r.detail

    def test_worst_source_wins_latency(self, cfg):
        proms = [(1.0, {"ttft_s_p95_s": 0.2}),
                 (1.0, {"ttft_s_p95_s": 0.9})]
        r = by_name(evaluate(cfg, [], proms=proms))["ttft_p95"]
        assert r.value == pytest.approx(0.9)

    def test_report_mode_now_defaults_to_newest_sample(self, cfg):
        # deterministic over archived artifacts: wall clock must not
        # leak in (these timestamps are years in the "past")
        pts = [(100.0 + i, 10 * i, 0, 2, 0.2) for i in range(10)]
        a = evaluate(cfg, series_for(pts))
        b = evaluate(cfg, series_for(pts))
        assert [(r.state, r.burn_long) for r in a] == \
               [(r.state, r.burn_long) for r in b]
        assert by_name(a)["availability"].state == STATE_OK


class TestSloWatch:
    def test_transitions_only_and_resolved(self, cfg):
        recs = []
        watch = SloWatch(cfg, emit=recs.append)
        burning = evaluate(cfg, series_for(
            [(990.0, 100, 0, 2, 0.1), (1000.0, 200, 100, 2, 0.1)]
        ))
        ok = evaluate(cfg, series_for(
            [(t, 10 * t, 0, 2, 0.1) for t in range(980, 1001)]
        ))
        watch.observe(ok, now=1.0)      # starts assumed ok: no records
        assert recs == []
        watch.observe(burning, now=2.0)
        watch.observe(burning, now=3.0)  # steady state: no repeat spam
        n_after_burn = len(recs)
        watch.observe(ok, now=4.0)
        assert n_after_burn == len(
            [r for r in recs if r["state"] != STATE_RESOLVED]
        )
        err = [r for r in recs if r["objective"] == "error_rate"]
        assert [r["state"] for r in err] == [
            STATE_BURNING, STATE_RESOLVED
        ]
        assert err[0]["prev"] == STATE_OK
        assert err[1]["prev"] == STATE_BURNING
        for r in recs:
            assert r["ev"] == "slo"

    def test_render_report_mentions_gate(self, cfg):
        res = evaluate(cfg, [])
        text = render_report(cfg, res)
        assert "gate: exit 1" in text
        assert "ttft_p95" in text


class TestSloReportCli:
    def _metrics_file(self, tmp_path, pts, name="metrics.jsonl"):
        p = tmp_path / name
        with p.open("w") as f:
            for row in rows(pts):
                f.write(json.dumps(row) + "\n")
        return p

    def _objectives(self, tmp_path):
        p = tmp_path / "slo.toml"
        p.write_text(OBJECTIVES_TOML)
        return p

    def test_clean_run_exits_zero(self, tmp_path):
        m = self._metrics_file(
            tmp_path, [(t, 10 * t, 0, 2, 0.3) for t in range(1, 20)]
        )
        res = CliRunner().invoke(telemetry_cli, [
            "slo-report", "--objectives", str(self._objectives(tmp_path)),
            "--metrics", str(m),
        ])
        assert res.exit_code == 0, res.output
        assert "gate: exit 0" in res.output

    def test_burning_run_exits_two_and_writes_artifacts(self, tmp_path):
        m = self._metrics_file(tmp_path, [
            (990.0, 100, 0, 1, 0.1), (1000.0, 200, 100, 1, 0.1),
        ])
        events = tmp_path / "slo_events.jsonl"
        out = tmp_path / "slo.json"
        res = CliRunner().invoke(telemetry_cli, [
            "slo-report", "--objectives", str(self._objectives(tmp_path)),
            "--metrics", str(m), "--events-out", str(events),
            "--json", str(out),
        ])
        assert res.exit_code == 2, res.output
        payload = json.loads(out.read_text())
        assert payload["exit"] == 2
        states = {r["objective"]: r["state"] for r in payload["results"]}
        assert states["error_rate"] == "burning"
        recs = [json.loads(ln)
                for ln in events.read_text().splitlines()]
        assert all(r["ev"] == "slo" for r in recs)
        assert any(r["state"] == "burning" for r in recs)

    def test_missing_data_exits_one(self, tmp_path):
        res = CliRunner().invoke(telemetry_cli, [
            "slo-report", "--objectives", str(self._objectives(tmp_path)),
        ])
        assert res.exit_code == 1, res.output

    def test_stale_prom_file_warns(self, tmp_path):
        prom = tmp_path / "router.prom"
        prom.write_text(
            "progen_router_ttft_seconds{quantile=\"0.95\"} 0.1\n"
        )
        old = time.time() - 3600
        os.utime(prom, (old, old))
        res = CliRunner().invoke(telemetry_cli, [
            "slo-report", "--objectives", str(self._objectives(tmp_path)),
            "--prom", str(prom),
        ])
        assert res.exit_code == 1, res.output
        assert "stale" in res.output

    def test_watch_mode_ticks_and_exits(self, tmp_path):
        m = self._metrics_file(tmp_path, [
            (990.0, 100, 0, 1, 0.1), (1000.0, 200, 100, 1, 0.1),
        ])
        res = CliRunner().invoke(telemetry_cli, [
            "slo-report", "--objectives", str(self._objectives(tmp_path)),
            "--metrics", str(m), "--watch", "0", "--max-ticks", "2",
            "--events-out", str(tmp_path / "w.jsonl"),
        ])
        # wall-clock "now" vs year-1970-ish sample times: everything in
        # the window is empty → ratio 0/0 ok... availability no data →
        # warn; the point here is only that watch terminates and gates
        assert res.exit_code in (1, 2), res.output

    def test_default_objectives_shipped_config(self, tmp_path):
        # no --objectives: the repo's configs/serving/slo.toml loads
        res = CliRunner().invoke(telemetry_cli, ["slo-report"])
        assert res.exit_code == 1, res.output  # no data → warn


class TestExitCodeContract:
    def test_precedence(self):
        from progen_tpu.telemetry.slo import SloResult

        ok = SloResult("a", "ratio", STATE_OK, 0.0, 0.0)
        warn = SloResult("b", "ratio", STATE_WARN, 1.0, 1.5)
        burn = SloResult("c", "ratio", STATE_BURNING, 9.0, 9.0)
        assert exit_code([ok]) == 0
        assert exit_code([ok, warn]) == 1
        assert exit_code([ok, warn, burn]) == 2
        assert exit_code([]) == 0
