"""`.env` loader + the shipped default `.env`."""

import os
from pathlib import Path

from progen_tpu.utils.env import load_env_file

REPO_ROOT = Path(__file__).resolve().parent.parent


class TestLoader:
    def test_parse_and_precedence(self, tmp_path, monkeypatch):
        f = tmp_path / ".env"
        f.write_text(
            "# comment\n"
            "export FOO=bar\n"
            "QUOTED='a b c'\n"
            "INLINE=x # trailing comment\n"
            "WINS=dotenv\n"
        )
        monkeypatch.setenv("WINS", "environ")
        saved = dict(os.environ)
        try:
            parsed = load_env_file(str(f))
            assert parsed["FOO"] == "bar" and os.environ["FOO"] == "bar"
            assert parsed["QUOTED"] == "a b c"
            assert parsed["INLINE"] == "x"
            # existing environment wins (dotenv override=False semantics)
            assert os.environ["WINS"] == "environ"
        finally:  # loader writes via setdefault: restore ALL keys it added
            os.environ.clear()
            os.environ.update(saved)

    def test_missing_file(self):
        assert load_env_file("/nonexistent/.env") == {}

    def test_dotenv_dir_expansion(self, tmp_path):
        # ${DOTENV_DIR} -> the .env file's own directory, keeping committed
        # repo-relative paths (XLA cache dir) checkout-path-agnostic
        f = tmp_path / ".env"
        f.write_text("CACHE=${DOTENV_DIR}/runs/xla_cache\n")
        saved = dict(os.environ)
        try:
            parsed = load_env_file(str(f))
        finally:
            os.environ.clear()
            os.environ.update(saved)
        assert parsed["CACHE"] == str(tmp_path.resolve() / "runs/xla_cache")

    def test_upward_search(self, tmp_path, monkeypatch):
        (tmp_path / ".env").write_text("UPWARD_FOUND=yes\n")
        sub = tmp_path / "a" / "b"
        sub.mkdir(parents=True)
        monkeypatch.chdir(sub)
        saved = dict(os.environ)
        try:
            assert load_env_file()["UPWARD_FOUND"] == "yes"
        finally:
            os.environ.clear()
            os.environ.update(saved)


class TestShippedDefaultEnv:
    def test_exists_and_parses(self, monkeypatch):
        # parse WITHOUT mutating this process's environment
        env_path = REPO_ROOT / ".env"
        assert env_path.exists()
        saved = dict(os.environ)
        try:
            parsed = load_env_file(str(env_path))
        finally:
            os.environ.clear()
            os.environ.update(saved)
        assert parsed  # non-empty

        # TPU-only --xla_tpu_* names are FATAL inside XLA_FLAGS on CPU-only
        # hosts (parse_flags_from_env aborts the process) — they must ride
        # LIBTPU_INIT_ARGS instead. Regression-pin that invariant.
        assert "xla_tpu" not in parsed.get("XLA_FLAGS", "")
        assert "--xla_tpu_enable_async_collective_fusion" in parsed.get(
            "LIBTPU_INIT_ARGS", ""
        )
        # the shipped cache dir must resolve under THIS checkout, not a
        # hardcoded absolute path from someone else's machine
        assert parsed["JAX_COMPILATION_CACHE_DIR"] == str(
            REPO_ROOT / "runs/xla_cache"
        )
