"""Hot weight reload: checkpoint swaps under live traffic.

The contract under test (serving/reload.py + engine.prepare_params/
commit_params): new weights of identical tree/shape/dtype swap in
between decode steps with ZERO recompiles and ZERO dropped requests;
anything else — corrupt bytes, truncated files, an incomplete save, an
incompatible architecture — is rejected on the background thread while
the current weights keep serving, untouched.
"""

import pathlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from progen_tpu.checkpoint import Package, get_checkpoint_fns
from progen_tpu.config import ProGenConfig
from progen_tpu.models.progen import ProGen
from progen_tpu.serving import (
    Request,
    Scheduler,
    ServeEngine,
    WeightReloader,
)

TINY = ProGenConfig(
    num_tokens=32,
    dim=32,
    seq_len=32,
    depth=2,
    window_size=8,
    global_mlp_depth=1,
    heads=2,
    dim_head=16,
    ff_mult=2,
    dtype="float32",
)


@pytest.fixture(scope="module")
def model_and_params():
    model = ProGen(TINY)
    tokens = jnp.zeros((1, TINY.seq_len), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), tokens)
    from flax.core import meta

    return model, meta.unbox(variables)["params"]


def _ckpt_name(path: str) -> str:
    return pathlib.Path(path).name


def _save(ck_dir, params, step=0, config=TINY):
    _, _, save = get_checkpoint_fns(str(ck_dir))
    return save(Package(step, {"params": params}, config.to_dict(), "run"))


def _first_leaf(tree):
    return np.asarray(jax.tree.leaves(tree)[0])


def _reload(reloader):
    """Kick + wait for the background load; commit stays the caller's."""
    assert reloader.request_reload()
    reloader.join(120)


class TestPackagePath:
    def test_restore_and_peek_report_source_dir(
        self, tmp_path, model_and_params
    ):
        """Reload decides 'is this new?' by comparing checkpoint dir
        names, so every restore surface must report where it read from."""
        _, params = model_and_params
        saved = _save(tmp_path / "ck", params)
        _, get_last, _ = get_checkpoint_fns(str(tmp_path / "ck"))
        pkg = get_last.restore_params()
        assert pkg.path is not None and _ckpt_name(pkg.path) == \
            _ckpt_name(saved)
        assert _ckpt_name(get_last.peek().path) == _ckpt_name(saved)


class TestHotSwap:
    def test_swap_under_live_traffic_no_recompile_no_drops(
        self, tmp_path, model_and_params
    ):
        """Serve from checkpoint A, stage B mid-decode, commit between
        steps: every request completes, the decode program never
        recompiles, and the engine ends up on B's weights."""
        model, params = model_and_params
        ck = tmp_path / "ck"
        name_a = _ckpt_name(_save(ck, params))
        params_b = jax.tree.map(lambda x: x * 1.5, params)

        engine = ServeEngine(model, params, max_slots=2, max_len=24)
        sched = Scheduler(engine)
        for i in range(3):
            ok, reason = sched.submit(Request(
                id=f"r{i}", prime=np.asarray([3 + i, 5], np.int32),
                length=20, seed=60 + i,
            ))
            assert ok, reason
        for _ in range(3):
            sched.step()  # decode program is compiled and running
        c0 = ServeEngine.decode_compile_count()

        name_b = _ckpt_name(_save(ck, params_b, step=1))
        reloader = WeightReloader(
            engine, ck, metrics=sched.metrics, current=name_a
        )
        _reload(reloader)
        # the serve loop's tick(): commit lands between decode steps
        assert reloader.maybe_commit() == name_b
        assert reloader.current == name_b and reloader.last_error is None

        _, comp = sched.run_to_completion(max_steps=200)
        done = {c.request_id for c in comp}
        assert done == {"r0", "r1", "r2"}  # zero dropped/rejected
        assert ServeEngine.decode_compile_count() == c0  # zero recompiles
        np.testing.assert_array_equal(
            _first_leaf(engine.params), _first_leaf(params) * 1.5
        )
        assert sched.metrics.counters["reloads"] == 1
        assert sched.metrics.counters["reload_rejected"] == 0

    def test_reload_onto_current_checkpoint_is_rejected(
        self, tmp_path, model_and_params
    ):
        model, params = model_and_params
        ck = tmp_path / "ck"
        name_a = _ckpt_name(_save(ck, params))
        engine = ServeEngine(model, params, max_slots=2, max_len=24)
        reloader = WeightReloader(engine, ck, current=name_a)
        _reload(reloader)
        assert reloader.maybe_commit() is None
        assert reloader.last_error == "no_new_checkpoint"

    def test_empty_store_is_rejected(self, tmp_path, model_and_params):
        model, params = model_and_params
        engine = ServeEngine(model, params, max_slots=2, max_len=24)
        reloader = WeightReloader(engine, tmp_path / "nothing_here")
        _reload(reloader)
        assert reloader.maybe_commit() is None
        assert reloader.last_error == "no_checkpoint"

    def test_int8_engine_requantizes_on_commit(
        self, tmp_path, model_and_params
    ):
        """An int8 engine must not serve new fp weights against stale
        quantized tables: commit swaps params, q-tables, and the
        calibration report together."""
        model, params = model_and_params
        ck = tmp_path / "ck"
        name_a = _ckpt_name(_save(ck, params))
        engine = ServeEngine(
            model, params, max_slots=2, max_len=24, quantize_int8=True
        )
        q_before = engine._q_params
        report_before = engine.quant_report
        assert report_before["quantized_leaves"] > 0

        _save(ck, jax.tree.map(lambda x: x * 1.5, params), step=1)
        reloader = WeightReloader(engine, ck, current=name_a)
        _reload(reloader)
        assert reloader.maybe_commit() is not None
        assert engine._q_params is not q_before
        assert engine.quant_report is not report_before
        assert engine.quant_report["quantized_leaves"] == \
            report_before["quantized_leaves"]


class TestRejectionPaths:
    """Every bad checkpoint is refused on the background thread; the
    live params must be bit-identical before and after the attempt."""

    def _engine_on_a(self, tmp_path, model, params):
        ck = tmp_path / "ck"
        name_a = _ckpt_name(_save(ck, params))
        engine = ServeEngine(model, params, max_slots=2, max_len=24)
        reloader = WeightReloader(engine, ck, current=name_a)
        return ck, engine, reloader

    def _state_files(self, ckpt_dir):
        return [
            f for f in (pathlib.Path(ckpt_dir) / "state").rglob("*")
            if f.is_file() and f.stat().st_size > 0
        ]

    def test_flipped_byte_quarantined_params_untouched(
        self, tmp_path, model_and_params
    ):
        model, params = model_and_params
        ck, engine, reloader = self._engine_on_a(tmp_path, model, params)
        target = _save(ck, jax.tree.map(lambda x: x * 2.0, params), step=1)
        victim = self._state_files(target)[0]
        blob = bytearray(victim.read_bytes())
        blob[0] ^= 0xFF
        victim.write_bytes(bytes(blob))

        before = _first_leaf(engine.params).copy()
        _reload(reloader)
        assert reloader.maybe_commit() is None
        # the digest walk quarantined B and fell back to A == current
        assert reloader.last_error == "no_new_checkpoint"
        assert any(
            p.name.endswith(".corrupt") for p in pathlib.Path(ck).iterdir()
        )
        np.testing.assert_array_equal(before, _first_leaf(engine.params))

    def test_truncated_file_quarantined_params_untouched(
        self, tmp_path, model_and_params
    ):
        model, params = model_and_params
        ck, engine, reloader = self._engine_on_a(tmp_path, model, params)
        target = _save(ck, jax.tree.map(lambda x: x + 1.0, params), step=1)
        victim = max(self._state_files(target), key=lambda f: f.stat().st_size)
        victim.write_bytes(victim.read_bytes()[: victim.stat().st_size // 2])

        before = _first_leaf(engine.params).copy()
        _reload(reloader)
        assert reloader.maybe_commit() is None
        assert reloader.last_error == "no_new_checkpoint"
        assert any(
            p.name.endswith(".corrupt") for p in pathlib.Path(ck).iterdir()
        )
        np.testing.assert_array_equal(before, _first_leaf(engine.params))

    def test_missing_meta_is_invisible_not_quarantined(
        self, tmp_path, model_and_params
    ):
        """No meta.json == save never finished: the dir is skipped by the
        walk (it may still be mid-write), not condemned as corrupt."""
        model, params = model_and_params
        ck, engine, reloader = self._engine_on_a(tmp_path, model, params)
        target = pathlib.Path(
            _save(ck, jax.tree.map(lambda x: x + 1.0, params), step=1)
        )
        (target / "meta.json").unlink()

        _reload(reloader)
        assert reloader.maybe_commit() is None
        assert reloader.last_error == "no_new_checkpoint"
        assert target.exists()  # still there, still meta-less
        assert not any(
            p.name.endswith(".corrupt") for p in pathlib.Path(ck).iterdir()
        )

    def test_incompatible_tree_rejected(self, tmp_path, model_and_params):
        """A checkpoint from a different architecture can never be
        hot-swapped (the compiled programs are shape-specialized): the
        compatibility check refuses it by name."""
        model, params = model_and_params
        import dataclasses

        other = dataclasses.replace(TINY, dim=16, dim_head=8)
        other_params = ProGen(other).init(
            jax.random.PRNGKey(1), jnp.zeros((1, other.seq_len), jnp.int32)
        )
        from flax.core import meta

        other_params = meta.unbox(other_params)["params"]
        ck = tmp_path / "ck"
        _save(ck, other_params, config=other)

        engine = ServeEngine(model, params, max_slots=2, max_len=24)
        before = _first_leaf(engine.params).copy()
        reloader = WeightReloader(engine, ck)
        _reload(reloader)
        assert reloader.maybe_commit() is None
        assert "incompatible" in reloader.last_error
        np.testing.assert_array_equal(before, _first_leaf(engine.params))


class TestPins:
    """The deploy controller's per-replica seam: a ``reload.pin``
    control file overrides newest-wins watching, and every pin outcome
    is answered through the adjacent ``reload.pin.ack``."""

    def _fleet_of_two(self, tmp_path, model_and_params):
        """Checkpoints A and B on disk, engine serving B (the newest)."""
        model, params = model_and_params
        ck = tmp_path / "ck"
        name_a = _ckpt_name(_save(ck, params))
        params_b = jax.tree.map(lambda x: x * 1.5, params)
        name_b = _ckpt_name(_save(ck, params_b, step=1))
        engine = ServeEngine(model, params_b, max_slots=2, max_len=24)
        pin_path = tmp_path / "reload.pin"
        reloader = WeightReloader(
            engine, ck, current=name_b, pin_path=pin_path
        )
        return ck, name_a, name_b, engine, pin_path, reloader

    def _ack(self, pin_path):
        ack = pin_path.with_name(pin_path.name + ".ack")
        import json

        return json.loads(ack.read_text())

    def test_pin_to_older_checkpoint_commits_and_acks(
        self, tmp_path, model_and_params
    ):
        """A pin is not 'newest-wins': the controller can roll a replica
        BACK to an older verified checkpoint by name."""
        _, params = model_and_params
        ck, name_a, name_b, engine, pin_path, reloader = \
            self._fleet_of_two(tmp_path, model_and_params)
        pin_path.write_text(name_a + "\n")
        assert reloader.poll_watch(0.0) is True
        reloader.join(120)
        assert reloader.maybe_commit() == name_a
        assert reloader.current == name_a
        np.testing.assert_array_equal(
            _first_leaf(engine.params), _first_leaf(params)
        )
        ack = self._ack(pin_path)
        assert ack["pin"] == name_a and ack["status"] == "committed"

    def test_pin_to_missing_name_rejected_weights_untouched(
        self, tmp_path, model_and_params
    ):
        ck, name_a, name_b, engine, pin_path, reloader = \
            self._fleet_of_two(tmp_path, model_and_params)
        before = _first_leaf(engine.params).copy()
        pin_path.write_text("ckpt_99999999\n")
        assert reloader.poll_watch(0.0) is True
        reloader.join(120)
        assert reloader.maybe_commit() is None
        assert reloader.last_error == "pin_unavailable"
        assert reloader.current == name_b
        np.testing.assert_array_equal(before, _first_leaf(engine.params))
        ack = self._ack(pin_path)
        assert ack["pin"] == "ckpt_99999999"
        assert ack["status"] == "rejected"
        assert ack["reason"] == "pin_unavailable"

    def test_rejected_pin_not_retried_until_it_changes(
        self, tmp_path, model_and_params
    ):
        """No hot retry loop on a pin that keeps failing — the watcher
        re-attempts only when the controller writes a different name."""
        ck, name_a, name_b, engine, pin_path, reloader = \
            self._fleet_of_two(tmp_path, model_and_params)
        pin_path.write_text("ckpt_99999999\n")
        assert reloader.poll_watch(0.0) is True
        reloader.join(120)
        assert reloader.maybe_commit() is None
        assert reloader.poll_watch(0.0) is False  # same bad pin: no kick
        pin_path.write_text(name_a + "\n")  # rollback to a real one
        assert reloader.poll_watch(0.0) is True
        reloader.join(120)
        assert reloader.maybe_commit() == name_a

    def test_pin_overrides_newest_wins(self, tmp_path, model_and_params):
        """While the canary bakes, the rest of the fleet is pinned to
        the fleet checkpoint: a newer dir on disk must NOT be loaded."""
        _, params = model_and_params
        ck, name_a, name_b, engine, pin_path, reloader = \
            self._fleet_of_two(tmp_path, model_and_params)
        # a newer checkpoint appears, but the pin says stay on B
        _save(ck, jax.tree.map(lambda x: x + 1.0, params), step=2)
        pin_path.write_text(name_b + "\n")
        assert reloader.poll_watch(0.0) is False
        assert reloader.current == name_b
        # the already-satisfied pin is still answered (the controller
        # needs the ack even when no reload was necessary)
        ack = self._ack(pin_path)
        assert ack["pin"] == name_b and ack["status"] == "committed"

    def test_pin_removal_resumes_newest_wins(
        self, tmp_path, model_and_params
    ):
        _, params = model_and_params
        ck, name_a, name_b, engine, pin_path, reloader = \
            self._fleet_of_two(tmp_path, model_and_params)
        pin_path.write_text(name_b + "\n")
        assert reloader.poll_watch(0.0) is False  # pinned in place
        name_c = _ckpt_name(
            _save(ck, jax.tree.map(lambda x: x + 1.0, params), step=2)
        )
        assert reloader.poll_watch(0.0) is False  # still pinned
        pin_path.unlink()
        assert reloader.poll_watch(0.0) is True  # back to newest-wins
        reloader.join(120)
        assert reloader.maybe_commit() == name_c

    def test_startup_pin_answered_without_reload(
        self, tmp_path, model_and_params
    ):
        """A pin file that predates the process: committed when startup
        restored exactly the pinned checkpoint, rejected when it had to
        fall back — the controller must never wait forever."""
        ck, name_a, name_b, engine, pin_path, reloader = \
            self._fleet_of_two(tmp_path, model_and_params)
        pin_path.write_text(name_b + "\n")
        reloader.note_startup_pin()
        ack = self._ack(pin_path)
        assert ack["pin"] == name_b and ack["status"] == "committed"

        pin_path.write_text("ckpt_99999999\n")
        reloader.note_startup_pin()
        ack = self._ack(pin_path)
        assert ack["status"] == "rejected"
        assert ack["reason"] == "pin_unavailable_at_startup"
        # and the watcher will not hot-retry the startup rejection
        assert reloader.poll_watch(0.0) is False


class TestWatcher:
    def test_poll_watch_kicks_on_new_checkpoint(
        self, tmp_path, model_and_params
    ):
        model, params = model_and_params
        ck = tmp_path / "ck"
        name_a = _ckpt_name(_save(ck, params))
        engine = ServeEngine(model, params, max_slots=2, max_len=24)
        reloader = WeightReloader(engine, ck, current=name_a)

        assert reloader.poll_watch(0.0) is False  # nothing newer
        name_b = _ckpt_name(
            _save(ck, jax.tree.map(lambda x: x * 1.5, params), step=1)
        )
        assert reloader.poll_watch(0.0) is True  # kicked
        reloader.join(120)
        assert reloader.maybe_commit() == name_b
        assert reloader.poll_watch(0.0) is False  # already current
