// Native TFRecord engine for progen_tpu's data layer.
//
// The reference delegates record IO to TensorFlow's C++ runtime
// (/root/reference/progen_transformer/data.py:7-21,48-62 via tf.io/tf.data);
// this is the equivalent native component for the TPU framework, exposed to
// Python over a minimal C ABI (ctypes — no pybind11 in the image).
//
// Responsibilities (the hot, per-record work the pure-Python codec in
// progen_tpu/data/tfrecord.py otherwise does in the interpreter):
//   * CRC-32C (Castagnoli), slice-by-8 table implementation, plus the
//     TFRecord mask ((crc >> 15 | crc << 17) + 0xa282ead8).
//   * Record framing: batch-parse a whole decompressed file buffer into
//     (offset, length) pairs with CRC verification in one call.
//   * tf.train.Example subset codec: encode/locate the single 'seq' bytes
//     feature (wire format per tensorflow/core/example/{example,feature}.proto).
//
// Build: g++ -O3 -shared -fPIC (see progen_tpu/data/_native.py, which
// compiles on first use and caches the .so).

#include <cstdint>
#include <cstring>

namespace {

// ---------------------------------------------------------------------------
// CRC-32C, slice-by-8
// ---------------------------------------------------------------------------

uint32_t kCrcTable[8][256];

// filled once at dlopen time (static initializer) — no lazy-init data race
// when the prefetch threads CRC concurrently
struct CrcTableInit {
  CrcTableInit() {
    const uint32_t poly = 0x82F63B78u;  // reversed Castagnoli
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t crc = i;
      for (int k = 0; k < 8; ++k)
        crc = (crc & 1) ? (crc >> 1) ^ poly : crc >> 1;
      kCrcTable[0][i] = crc;
    }
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t crc = kCrcTable[0][i];
      for (int t = 1; t < 8; ++t) {
        crc = kCrcTable[0][crc & 0xFF] ^ (crc >> 8);
        kCrcTable[t][i] = crc;
      }
    }
  }
};
const CrcTableInit crc_table_init;

uint32_t crc32c(const uint8_t* p, long n) {
  uint32_t crc = 0xFFFFFFFFu;
  while (n >= 8) {
    uint32_t lo, hi;
    std::memcpy(&lo, p, 4);
    std::memcpy(&hi, p + 4, 4);
    lo ^= crc;
    crc = kCrcTable[7][lo & 0xFF] ^ kCrcTable[6][(lo >> 8) & 0xFF] ^
          kCrcTable[5][(lo >> 16) & 0xFF] ^ kCrcTable[4][lo >> 24] ^
          kCrcTable[3][hi & 0xFF] ^ kCrcTable[2][(hi >> 8) & 0xFF] ^
          kCrcTable[1][(hi >> 16) & 0xFF] ^ kCrcTable[0][hi >> 24];
    p += 8;
    n -= 8;
  }
  while (n-- > 0) crc = kCrcTable[0][(crc ^ *p++) & 0xFF] ^ (crc >> 8);
  return crc ^ 0xFFFFFFFFu;
}

uint32_t masked_crc(const uint8_t* p, long n) {
  uint32_t c = crc32c(p, n);
  return ((c >> 15) | (c << 17)) + 0xA282EAD8u;
}

uint32_t load_le32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;  // image is little-endian (x86/ARM); TFRecord is LE on disk
}

uint64_t load_le64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;
}

// ---------------------------------------------------------------------------
// protobuf wire helpers (subset: varint + length-delimited)
// ---------------------------------------------------------------------------

int read_varint(const uint8_t* buf, long len, long* pos, uint64_t* out) {
  uint64_t result = 0;
  int shift = 0;
  while (*pos < len && shift < 64) {
    uint8_t b = buf[(*pos)++];
    result |= static_cast<uint64_t>(b & 0x7F) << shift;
    if (!(b & 0x80)) {
      *out = result;
      return 0;
    }
    shift += 7;
  }
  return -1;
}

long varint_size(uint64_t v) {
  long n = 1;
  while (v >= 0x80) {
    v >>= 7;
    ++n;
  }
  return n;
}

void write_varint(uint8_t** p, uint64_t v) {
  while (v >= 0x80) {
    *(*p)++ = static_cast<uint8_t>(v) | 0x80;
    v >>= 7;
  }
  *(*p)++ = static_cast<uint8_t>(v);
}

// Scan a length-delimited message for field `field` (wire type 2); returns 0
// and sets (off, flen) for the FIRST match, else -1. Skips unknown fields.
int find_field(const uint8_t* buf, long len, uint32_t field, long* off,
               long* flen) {
  long pos = 0;
  while (pos < len) {
    uint64_t tag;
    if (read_varint(buf, len, &pos, &tag)) return -1;
    uint32_t f = static_cast<uint32_t>(tag >> 3);
    uint32_t wire = tag & 0x7;
    if (wire == 2) {
      uint64_t ln;
      if (read_varint(buf, len, &pos, &ln)) return -1;
      // guard the signed cast: a huge varint must not wrap negative
      if (ln > static_cast<uint64_t>(len) ||
          pos + static_cast<long>(ln) > len)
        return -1;
      if (f == field) {
        *off = pos;
        *flen = static_cast<long>(ln);
        return 0;
      }
      pos += static_cast<long>(ln);
    } else if (wire == 0) {
      uint64_t v;
      if (read_varint(buf, len, &pos, &v)) return -1;
    } else if (wire == 5) {
      pos += 4;
    } else if (wire == 1) {
      pos += 8;
    } else {
      return -1;
    }
  }
  return -1;
}

}  // namespace

extern "C" {

uint32_t tfio_crc32c(const uint8_t* data, long len) { return crc32c(data, len); }

uint32_t tfio_masked_crc(const uint8_t* data, long len) {
  return masked_crc(data, len);
}

// Batch-parse TFRecord framing from a decompressed buffer. Fills
// offsets[i]/lengths[i] with each record payload's position. Returns the
// record count, or -(1+byte_offset) on a framing/CRC error.
long tfio_parse_records(const uint8_t* buf, long len, long* offsets,
                        long* lengths, long max_records, int verify_crc) {
  long pos = 0, count = 0;
  while (pos < len && count < max_records) {
    if (pos + 12 > len) return -(1 + pos);
    uint64_t rec_len = load_le64(buf + pos);
    if (rec_len > static_cast<uint64_t>(len)) return -(1 + pos);
    if (verify_crc && load_le32(buf + pos + 8) != masked_crc(buf + pos, 8))
      return -(1 + pos);
    long payload = pos + 12;
    if (payload + static_cast<long>(rec_len) + 4 > len) return -(1 + pos);
    if (verify_crc &&
        load_le32(buf + payload + rec_len) != masked_crc(buf + payload, rec_len))
      return -(1 + pos);
    offsets[count] = payload;
    lengths[count] = static_cast<long>(rec_len);
    ++count;
    pos = payload + static_cast<long>(rec_len) + 4;
  }
  return count;
}

// Locate the 'seq' bytes feature inside a serialized Example. Returns the
// value length and sets *out_off to its offset within `payload`, or -1.
long tfio_example_seq(const uint8_t* payload, long len, const char* key,
                      long key_len, long* out_off) {
  long foff, flen;
  // Example.features (field 1)
  if (find_field(payload, len, 1, &foff, &flen)) return -1;
  const uint8_t* features = payload + foff;
  // iterate Features.feature map entries (field 1, repeated)
  long pos = 0;
  while (pos < flen) {
    long eoff, elen;
    if (find_field(features + pos, flen - pos, 1, &eoff, &elen)) return -1;
    const uint8_t* entry = features + pos + eoff;
    long koff, klen;
    if (find_field(entry, elen, 1, &koff, &klen) == 0 && klen == key_len &&
        std::memcmp(entry + koff, key, key_len) == 0) {
      long voff, vlen;
      if (find_field(entry, elen, 2, &voff, &vlen)) return -1;  // Feature
      long bloff, bllen;
      if (find_field(entry + voff, vlen, 1, &bloff, &bllen)) return -1;  // BytesList
      long soff, slen;
      if (find_field(entry + voff + bloff, bllen, 1, &soff, &slen)) return -1;
      *out_off = (entry + voff + bloff + soff) - payload;
      return slen;
    }
    pos += eoff + elen;
  }
  return -1;
}

// Size of the full framed record tfio_encode_record would emit.
long tfio_encoded_size(long seq_len, long key_len) {
  long bytes_list = 1 + varint_size(seq_len) + seq_len;
  long feature = 1 + varint_size(bytes_list) + bytes_list;
  long entry = 1 + varint_size(key_len) + key_len + 1 +
               varint_size(feature) + feature;
  long features = 1 + varint_size(entry) + entry;
  long example = 1 + varint_size(features) + features;
  return 12 + example + 4;  // framing header + payload + crc
}

// Encode one framed record: Example{features{key: bytes_list([seq])}} with
// TFRecord framing. Returns bytes written, or -1 if out_cap is too small.
long tfio_encode_record(const uint8_t* seq, long seq_len, const char* key,
                        long key_len, uint8_t* out, long out_cap) {
  long total = tfio_encoded_size(seq_len, key_len);
  if (total > out_cap) return -1;

  long bytes_list = 1 + varint_size(seq_len) + seq_len;
  long feature = 1 + varint_size(bytes_list) + bytes_list;
  long entry = 1 + varint_size(key_len) + key_len + 1 +
               varint_size(feature) + feature;
  long features = 1 + varint_size(entry) + entry;
  long example = 1 + varint_size(features) + features;

  uint8_t* p = out;
  // framing header
  uint64_t ex64 = static_cast<uint64_t>(example);
  std::memcpy(p, &ex64, 8);
  uint32_t hcrc = masked_crc(p, 8);
  std::memcpy(p + 8, &hcrc, 4);
  p += 12;
  uint8_t* payload = p;
  // Example.features
  *p++ = (1 << 3) | 2;
  write_varint(&p, features);
  // Features.feature entry
  *p++ = (1 << 3) | 2;
  write_varint(&p, entry);
  //   key
  *p++ = (1 << 3) | 2;
  write_varint(&p, key_len);
  std::memcpy(p, key, key_len);
  p += key_len;
  //   value: Feature.bytes_list
  *p++ = (2 << 3) | 2;
  write_varint(&p, feature);
  *p++ = (1 << 3) | 2;
  write_varint(&p, bytes_list);
  //     BytesList.value
  *p++ = (1 << 3) | 2;
  write_varint(&p, seq_len);
  std::memcpy(p, seq, seq_len);
  p += seq_len;
  // payload crc
  uint32_t pcrc = masked_crc(payload, example);
  std::memcpy(p, &pcrc, 4);
  p += 4;
  return p - out;
}

// Batch collation: raw sequence bytes -> (n, seq_len+1) int32 rows, the
// hot per-batch loop of the training input pipeline (truncate to seq_len,
// +offset each byte, right-pad 0, and a 0-valued BOS column at position 0
// — progen_tpu/data/dataset.py collate(), mirroring the reference's
// tf.data map at /root/reference/progen_transformer/data.py:30-35,67-69).
// recs: per-record base pointers; lengths: per-record byte counts.
void tfio_collate(const uint8_t** recs, const long* lengths, long n,
                  long seq_len, long offset, int32_t* out) {
  const long row_len = seq_len + 1;
  for (long i = 0; i < n; ++i) {
    int32_t* row = out + i * row_len;
    long m = lengths[i] < seq_len ? lengths[i] : seq_len;
    row[0] = 0;  // BOS
    const uint8_t* src = recs[i];
    for (long j = 0; j < m; ++j)
      row[j + 1] = static_cast<int32_t>(src[j]) + static_cast<int32_t>(offset);
    std::memset(row + 1 + m, 0, sizeof(int32_t) * (seq_len - m));
  }
}

}  // extern "C"
